"""Task life-cycle state machine: launch / exec.

Parity: /root/reference/sky/execution.py:30-565 (`Stage` enum, `_execute`
stage runner, `launch`, `exec`). Same shape; stages CLONE_DISK is dropped
(no disk cloning on TPU-VMs) and a CHECKPOINT stage is added to wire the
first-class checkpoint-dir contract before EXEC.
"""
from __future__ import annotations

import enum
from typing import Any, List, Optional, Union

from skypilot_tpu import admin_policy
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.backends import backend_utils
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.resources import Resources
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import dag_utils

logger = sky_logging.init_logger(__name__)


class Stage(enum.Enum):
    OPTIMIZE = enum.auto()
    PROVISION = enum.auto()
    SYNC_WORKDIR = enum.auto()
    SYNC_FILE_MOUNTS = enum.auto()
    SETUP = enum.auto()
    PRE_EXEC = enum.auto()
    EXEC = enum.auto()
    DOWN = enum.auto()


def _execute(
    entrypoint: Union[task_lib.Task, dag_lib.Dag],
    *,
    cluster_name: Optional[str] = None,
    dryrun: bool = False,
    down: bool = False,
    stream_logs: bool = True,
    backend: Optional[slice_backend.SliceBackend] = None,
    optimize_target: optimizer_lib.OptimizeTarget = (
        optimizer_lib.OptimizeTarget.COST),
    stages: Optional[List[Stage]] = None,
    detach_run: bool = False,
    idle_minutes_to_autostop: Optional[int] = None,
    retry_until_up: bool = False,
    no_setup: bool = False,
) -> Optional[int]:
    """Run the requested stages for a one-task DAG; returns the job id."""
    dag = dag_utils.convert_entrypoint_to_dag(entrypoint)
    dag = admin_policy.apply(dag)
    if len(dag.tasks) != 1:
        raise exceptions.InvalidTaskError(
            'launch/exec take exactly one task; use managed jobs for '
            'pipelines.')
    task = dag.tasks[0]
    if cluster_name is None:
        cluster_name = f'sky-{common_utils.get_user_hash()[:4]}-' \
                       f'{common_utils.get_user()[:8]}'
    backend = backend or slice_backend.SliceBackend()
    backend.register_info(
        minimize_target=optimize_target,
        requested_features=_requested_features(task, down,
                                               idle_minutes_to_autostop))
    stages = stages or list(Stage)

    # Stage-runtime decomposition: time-to-first-step is the north-star
    # denominator (BASELINE.md); every invocation records where its
    # wall-clock went (usage_lib; surfaced by `sky status`), and every
    # stage is journaled into the cluster's flight recorder
    # (observability/events.py; surfaced by `sky status --events`).
    from skypilot_tpu import usage_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import rich_utils  # pylint: disable=import-outside-toplevel
    entrypoint_name = 'launch' if Stage.PROVISION in stages else 'exec'
    run_rec = usage_lib.RunRecord(entrypoint_name, cluster_name)
    journal = events_lib.cluster_journal(cluster_name)
    journal.append(f'{entrypoint_name}_start', task=task.name,
                   dryrun=dryrun)
    job_id: Optional[int] = None
    final_status = 'ok'
    try:
        to_provision: Optional[Resources] = None
        if Stage.OPTIMIZE in stages:
            with run_rec.stage('optimize'), \
                    events_lib.ControlSpan(journal, 'optimize'), \
                    rich_utils.safe_status(
                        'Optimizing resource placement',
                        enabled=not stream_logs):
                existing = backend.check_existing_cluster(cluster_name,
                                                          task)
                if existing is None:
                    optimizer_lib.Optimizer.optimize(
                        dag, minimize=optimize_target,
                        quiet=not stream_logs)
                    to_provision = task.best_resources

        handle = None
        if Stage.PROVISION in stages:
            with run_rec.stage('provision'), \
                    events_lib.ControlSpan(journal, 'provision'), \
                    rich_utils.safe_status(
                        f'Launching cluster {cluster_name}',
                        enabled=not stream_logs):
                handle = backend.provision(task, to_provision,
                                           dryrun=dryrun,
                                           stream_logs=stream_logs,
                                           cluster_name=cluster_name,
                                           retry_until_up=retry_until_up)
            if dryrun:
                return None
            assert handle is not None
        else:
            handle = backend_utils.check_cluster_available(cluster_name)

        if Stage.SYNC_WORKDIR in stages and task.workdir is not None:
            with run_rec.stage('sync_workdir'), \
                    events_lib.ControlSpan(journal, 'sync_workdir'), \
                    rich_utils.safe_status('Syncing workdir',
                                           enabled=not stream_logs):
                backend.sync_workdir(handle, task.workdir)

        if Stage.SYNC_FILE_MOUNTS in stages:
            if task.file_mounts or task.storage_mounts:
                with run_rec.stage('sync_file_mounts'), \
                        events_lib.ControlSpan(journal,
                                               'sync_file_mounts'), \
                        rich_utils.safe_status('Syncing file mounts',
                                               enabled=not stream_logs):
                    backend.sync_file_mounts(handle, task.file_mounts,
                                             task.storage_mounts)

        if Stage.SETUP in stages and not no_setup:
            with run_rec.stage('setup'), \
                    events_lib.ControlSpan(journal, 'setup'), \
                    rich_utils.safe_status('Running setup',
                                           enabled=not stream_logs):
                backend.setup(handle, task)

        if Stage.PRE_EXEC in stages:
            if idle_minutes_to_autostop is not None:
                with run_rec.stage('pre_exec'):
                    backend.set_autostop(handle, idle_minutes_to_autostop,
                                         down)

        if Stage.EXEC in stages:
            # exec_submit covers handing the job to the cluster, not
            # the job's own runtime (that is the job's, not ours).
            with run_rec.stage('exec_submit'), \
                    events_lib.ControlSpan(journal, 'exec') as span, \
                    rich_utils.safe_status('Submitting job',
                                           enabled=not stream_logs):
                job_id = backend.execute(handle, task,
                                         detach_run=detach_run)
                span.add(job_id=job_id)

        if (Stage.DOWN in stages and down and
                idle_minutes_to_autostop is None):
            backend.teardown(handle, terminate=True)
        return job_id
    except BaseException as e:  # noqa: B036 — re-raised below
        final_status = type(e).__name__
        raise
    finally:
        run_rec.finalize()
        journal.append(
            f'{entrypoint_name}_end', status=final_status, job_id=job_id,
            time_to_first_step_s=run_rec.time_to_first_step)


def _requested_features(task: task_lib.Task, down: bool,
                        idle_minutes: Optional[int]) -> set:
    from skypilot_tpu.clouds import cloud as cloud_lib  # pylint: disable=import-outside-toplevel
    features = set()
    for resources in task.resources:
        features |= resources.get_required_cloud_features()
    if idle_minutes is not None and not down:
        features.add(cloud_lib.CloudImplementationFeatures.STOP)
    if task.num_nodes > 1:
        features.add(cloud_lib.CloudImplementationFeatures.MULTI_NODE)
    return features


def launch(
    task: Union[task_lib.Task, dag_lib.Dag],
    cluster_name: Optional[str] = None,
    *,
    dryrun: bool = False,
    down: bool = False,
    stream_logs: bool = True,
    backend: Optional[slice_backend.SliceBackend] = None,
    optimize_target: optimizer_lib.OptimizeTarget = (
        optimizer_lib.OptimizeTarget.COST),
    detach_run: bool = False,
    idle_minutes_to_autostop: Optional[int] = None,
    retry_until_up: bool = False,
    no_setup: bool = False,
) -> Optional[int]:
    """Provision (or reuse) a cluster and run the task on it.

    Parity: reference execution.py:344.
    """
    return _execute(
        task,
        cluster_name=cluster_name,
        dryrun=dryrun,
        down=down,
        stream_logs=stream_logs,
        backend=backend,
        optimize_target=optimize_target,
        detach_run=detach_run,
        idle_minutes_to_autostop=idle_minutes_to_autostop,
        retry_until_up=retry_until_up,
        no_setup=no_setup,
    )


def exec(  # pylint: disable=redefined-builtin
    task: Union[task_lib.Task, dag_lib.Dag],
    cluster_name: str,
    *,
    dryrun: bool = False,
    down: bool = False,
    stream_logs: bool = True,
    backend: Optional[slice_backend.SliceBackend] = None,
    detach_run: bool = False,
) -> Optional[int]:
    """Run a task on an existing cluster, skipping provision/setup.

    Parity: reference execution.py:477.
    """
    handle = backend_utils.check_cluster_available(cluster_name)
    # Stale-runtime guard (reference backend_utils.py:2593): warn when
    # the cluster's app tree no longer matches this client.
    skew = backend_utils.check_remote_runtime_version(handle)
    if skew:
        logger.warning(skew)
    return _execute(
        task,
        cluster_name=cluster_name,
        dryrun=dryrun,
        down=down,
        stream_logs=stream_logs,
        backend=backend,
        detach_run=detach_run,
        stages=[Stage.SYNC_WORKDIR, Stage.SYNC_FILE_MOUNTS, Stage.EXEC],
    )
