"""Step-timestamp logger used by the bench harness.

Parity: /root/reference/sky/callbacks/sky_callback/base.py — `init()`
then `step()` (context manager) or `on_step_begin()/on_step_end()`;
timestamps are flushed to `<log_dir>/summary.json` so `bench` can
compute $/step and time-to-K-steps without touching user code
internals.
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional

ENV_LOG_DIR = 'SKYTPU_BENCHMARK_LOG_DIR'
DEFAULT_LOG_DIR = '~/.skytpu/benchmark_logs'
SUMMARY_FILE = 'summary.json'

_instance: Optional['SkyTpuCallback'] = None


class SkyTpuCallback:

    def __init__(self, log_dir: Optional[str] = None,
                 total_steps: Optional[int] = None,
                 flush_every: int = 10) -> None:
        log_dir = log_dir or os.environ.get(ENV_LOG_DIR, DEFAULT_LOG_DIR)
        self.log_dir = os.path.expanduser(log_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        self.total_steps = total_steps
        self.flush_every = flush_every
        self.start_time = time.time()
        self.step_begins: list = []
        self.step_ends: list = []
        self._lock = threading.Lock()
        atexit.register(self.flush)

    def on_step_begin(self) -> None:
        with self._lock:
            self.step_begins.append(time.time())

    def on_step_end(self) -> None:
        with self._lock:
            self.step_ends.append(time.time())
            if len(self.step_ends) % self.flush_every == 0:
                self._flush_no_lock()

    @contextlib.contextmanager
    def step(self):
        self.on_step_begin()
        try:
            yield
        finally:
            self.on_step_end()

    def summary(self) -> Dict[str, Any]:
        steps = len(self.step_ends)
        elapsed = (self.step_ends[-1] - self.start_time) if steps else 0.0
        seconds_per_step = None
        if steps >= 2:
            # Steady-state: ignore the first (compile-heavy) step.
            seconds_per_step = ((self.step_ends[-1] - self.step_ends[0]) /
                                (steps - 1))
        return {
            'start_time': self.start_time,
            'num_steps': steps,
            'elapsed_seconds': elapsed,
            'seconds_per_step': seconds_per_step,
            'first_step_seconds':
                (self.step_ends[0] - self.start_time) if steps else None,
            'total_steps': self.total_steps,
            'last_step_time': self.step_ends[-1] if steps else None,
        }

    def flush(self) -> None:
        with self._lock:
            self._flush_no_lock()

    def _flush_no_lock(self) -> None:
        path = os.path.join(self.log_dir, SUMMARY_FILE)
        tmp = path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(self.summary(), f)
        os.replace(tmp, path)


def init(log_dir: Optional[str] = None,
         total_steps: Optional[int] = None) -> SkyTpuCallback:
    global _instance
    if _instance is None:
        _instance = SkyTpuCallback(log_dir=log_dir,
                                   total_steps=total_steps)
        return _instance
    # Singleton exists: later callers' arguments must not silently
    # vanish — a different log_dir is an error (two destinations cannot
    # both hold the summary; checked FIRST so a rejected call leaves
    # the singleton untouched), then total_steps is adopted.
    if (log_dir is not None and
            os.path.expanduser(log_dir) != _instance.log_dir):
        raise RuntimeError(
            f'skytpu callback already initialized with log_dir='
            f'{_instance.log_dir!r}; cannot switch to {log_dir!r}.')
    if total_steps is not None:
        _instance.total_steps = total_steps
    return _instance


def _require() -> SkyTpuCallback:
    if _instance is None:
        raise RuntimeError('call skytpu_callback init() first')
    return _instance


def on_step_begin() -> None:
    _require().on_step_begin()


def on_step_end() -> None:
    _require().on_step_end()


def step():
    return _require().step()
