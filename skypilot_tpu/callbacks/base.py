"""Step-timestamp logger used by the bench harness.

Parity: /root/reference/sky/callbacks/sky_callback/base.py — `init()`
then `step()` (context manager) or `on_step_begin()/on_step_end()`;
timestamps are flushed to `<log_dir>/summary.json` so `bench` can
compute $/step and time-to-K-steps without touching user code
internals.

Training telemetry (observability/metrics.py): every step feeds the
process-global registry (steps, step-seconds histogram, tokens/s,
data-wait, peak memory), and `summary()` splits compute time from
data-wait — `seconds_per_step` (inter-end deltas, kept for
compatibility) folds data-loading gaps into step time, while
`compute_seconds_per_step` (begin→end) and `data_wait_seconds`
(end→next-begin gaps) report the two separately, so "the input
pipeline is the bottleneck" is a number, not a guess.  The hooks
`record_data_wait` / `record_peak_memory` are fed by
data/prefetch.py and models/train.py.

Set SKYTPU_JAX_PROFILE_DIR to capture a jax.profiler trace for the
whole run (started at init(), stopped atexit) — the device-level
companion to this host-level telemetry.
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib

logger = sky_logging.init_logger(__name__)

ENV_LOG_DIR = 'SKYTPU_BENCHMARK_LOG_DIR'
ENV_PROFILE_DIR = 'SKYTPU_JAX_PROFILE_DIR'
DEFAULT_LOG_DIR = '~/.skytpu/benchmark_logs'
SUMMARY_FILE = 'summary.json'

_M_STEPS = metrics_lib.counter(
    'skytpu_train_steps_total', 'Optimizer steps completed.')
_M_STEP_SECONDS = metrics_lib.histogram(
    'skytpu_train_step_seconds',
    'Wall seconds per step (on_step_begin -> on_step_end).',
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0, 300.0))
_M_DATA_WAIT = metrics_lib.counter(
    'skytpu_train_data_wait_seconds_total',
    'Seconds the training loop blocked waiting for input batches.')
_M_TOKENS_PER_S = metrics_lib.gauge(
    'skytpu_train_tokens_per_s',
    'Training throughput over the steady-state steps '
    '(needs tokens_per_step).')
_M_PEAK_MEMORY = metrics_lib.gauge(
    'skytpu_train_peak_memory_bytes',
    "The compiled step's peak temp allocation (XLA "
    'CompiledMemoryStats).')

_instance: Optional['SkyTpuCallback'] = None


class SkyTpuCallback:

    def __init__(self, log_dir: Optional[str] = None,
                 total_steps: Optional[int] = None,
                 flush_every: int = 10,
                 tokens_per_step: Optional[int] = None) -> None:
        log_dir = log_dir or os.environ.get(ENV_LOG_DIR, DEFAULT_LOG_DIR)
        self.log_dir = os.path.expanduser(log_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        self.total_steps = total_steps
        self.tokens_per_step = tokens_per_step
        self.flush_every = flush_every
        self.start_time = time.time()
        self.step_begins: list = []
        self.step_ends: list = []
        self.prefetch_wait_seconds = 0.0   # fed by record_data_wait
        self.peak_memory_bytes: Optional[int] = None
        self._lock = threading.Lock()
        atexit.register(self.flush)
        self._maybe_start_profiler()

    def _maybe_start_profiler(self) -> None:
        """SKYTPU_JAX_PROFILE_DIR=<dir>: one jax.profiler trace for the
        whole run (view with TensorBoard / Perfetto); never fatal — a
        CPU-only box without profiler support still trains."""
        profile_dir = os.environ.get(ENV_PROFILE_DIR)
        if not profile_dir:
            return
        try:
            import jax  # pylint: disable=import-outside-toplevel
            jax.profiler.start_trace(os.path.expanduser(profile_dir))
            atexit.register(jax.profiler.stop_trace)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'skytpu callback: jax.profiler trace not '
                           f'started ({type(e).__name__}: {e})')

    def on_step_begin(self) -> None:
        with self._lock:
            self.step_begins.append(time.time())

    def on_step_end(self) -> None:
        # Snapshot under the lock, write OUTSIDE it: the summary file
        # write must never stall a concurrent on_step_begin (sky lint
        # blocking-under-lock).
        summary = None
        with self._lock:
            now = time.time()
            self.step_ends.append(now)
            n = len(self.step_ends)
            if len(self.step_begins) >= n:
                _M_STEP_SECONDS.observe(now - self.step_begins[n - 1])
            if n % self.flush_every == 0:
                summary = self.summary()
        if summary is not None:
            self._write_summary(summary)
        _M_STEPS.inc()
        if self.tokens_per_step:
            rate = self._tokens_per_s()
            if rate is not None:
                _M_TOKENS_PER_S.set(rate)

    def _tokens_per_s(self) -> Optional[float]:
        compute = self._compute_seconds_per_step()
        if compute is None or compute <= 0 or not self.tokens_per_step:
            return None
        return self.tokens_per_step / compute

    @contextlib.contextmanager
    def step(self):
        self.on_step_begin()
        try:
            yield
        finally:
            self.on_step_end()

    def _compute_seconds_per_step(self) -> Optional[float]:
        """Mean begin→end duration over the steady-state steps (the
        first, compile-heavy step is excluded when there are >= 2):
        pure step compute, with data-loading gaps OUT."""
        n = min(len(self.step_begins), len(self.step_ends))
        durations = [self.step_ends[i] - self.step_begins[i]
                     for i in range(n)]
        if not durations:
            return None
        if len(durations) >= 2:
            durations = durations[1:]
        return sum(durations) / len(durations)

    def _data_wait_seconds(self) -> float:
        """Total end→next-begin gap: time the loop spent NOT inside a
        step (fetching batches, checkpointing, logging).  This is what
        `seconds_per_step`'s inter-end deltas silently folded into
        step time."""
        n = min(len(self.step_begins), len(self.step_ends))
        return sum(max(0.0, self.step_begins[i] - self.step_ends[i - 1])
                   for i in range(1, n))

    def summary(self) -> Dict[str, Any]:
        steps = len(self.step_ends)
        elapsed = (self.step_ends[-1] - self.start_time) if steps else 0.0
        seconds_per_step = None
        if steps >= 2:
            # Steady-state: ignore the first (compile-heavy) step.
            # NOTE: inter-END deltas — includes data-wait gaps; kept
            # for compatibility with existing bench consumers.  The
            # split view is compute_seconds_per_step +
            # data_wait_seconds below.
            seconds_per_step = ((self.step_ends[-1] - self.step_ends[0]) /
                                (steps - 1))
        return {
            'start_time': self.start_time,
            'num_steps': steps,
            'elapsed_seconds': elapsed,
            'seconds_per_step': seconds_per_step,
            'compute_seconds_per_step': self._compute_seconds_per_step(),
            'data_wait_seconds': self._data_wait_seconds(),
            'prefetch_wait_seconds': self.prefetch_wait_seconds,
            'tokens_per_step': self.tokens_per_step,
            'tokens_per_s': self._tokens_per_s(),
            'peak_memory_bytes': self.peak_memory_bytes,
            'first_step_seconds':
                (self.step_ends[0] - self.start_time) if steps else None,
            'total_steps': self.total_steps,
            'last_step_time': self.step_ends[-1] if steps else None,
        }

    def flush(self) -> None:
        with self._lock:
            summary = self.summary()
        self._write_summary(summary)

    def _write_summary(self, summary: Dict[str, Any]) -> None:
        """File I/O only — callers snapshot state under the lock and
        write with it RELEASED, so flushes never block the step path."""
        path = os.path.join(self.log_dir, SUMMARY_FILE)
        tmp = path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(summary, f)
        os.replace(tmp, path)


def init(log_dir: Optional[str] = None,
         total_steps: Optional[int] = None,
         tokens_per_step: Optional[int] = None) -> SkyTpuCallback:
    global _instance
    if _instance is None:
        _instance = SkyTpuCallback(log_dir=log_dir,
                                   total_steps=total_steps,
                                   tokens_per_step=tokens_per_step)
        return _instance
    # Singleton exists: later callers' arguments must not silently
    # vanish — a different log_dir is an error (two destinations cannot
    # both hold the summary; checked FIRST so a rejected call leaves
    # the singleton untouched), then total_steps is adopted.
    if (log_dir is not None and
            os.path.expanduser(log_dir) != _instance.log_dir):
        raise RuntimeError(
            f'skytpu callback already initialized with log_dir='
            f'{_instance.log_dir!r}; cannot switch to {log_dir!r}.')
    if total_steps is not None:
        _instance.total_steps = total_steps
    if tokens_per_step is not None:
        _instance.tokens_per_step = tokens_per_step
    return _instance


def _require() -> SkyTpuCallback:
    if _instance is None:
        raise RuntimeError('call skytpu_callback init() first')
    return _instance


def on_step_begin() -> None:
    _require().on_step_begin()


def on_step_end() -> None:
    _require().on_step_end()


def step():
    return _require().step()


# ------------------------------------------------------------- hooks
# Fed by data/prefetch.py and models/train.py; safe to call whether or
# not init() ran (the registry metric always updates, the summary
# field only with a live singleton).


def record_data_wait(seconds: float) -> None:
    """The consumer blocked `seconds` waiting for an input batch
    (DevicePrefetcher reports its queue-get block time here)."""
    if seconds <= 0:
        return
    _M_DATA_WAIT.inc(seconds)
    if _instance is not None:
        _instance.prefetch_wait_seconds += seconds


def record_peak_memory(nbytes: int) -> None:
    """The compiled train step's peak temp allocation
    (models/train.py::compiled_peak_memory feeds this)."""
    _M_PEAK_MEMORY.set(nbytes)
    if _instance is not None:
        _instance.peak_memory_bytes = int(nbytes)
