"""skytpu_callback: in-training-loop step timestamping for `bench`.

Parity: /root/reference/sky/callbacks/sky_callback/ (init/on_step_begin/
step context + framework integrations writing benchmark summaries).
Zero framework dependencies: user training code calls `init()` once and
`step()` per step; summaries land in BENCHMARK_LOG_DIR for the bench
harness to aggregate.
"""
from skypilot_tpu.callbacks.base import SkyTpuCallback
from skypilot_tpu.callbacks.base import init
from skypilot_tpu.callbacks.base import on_step_begin
from skypilot_tpu.callbacks.base import on_step_end
from skypilot_tpu.callbacks.base import step

__all__ = ['SkyTpuCallback', 'init', 'on_step_begin', 'on_step_end',
           'step']
