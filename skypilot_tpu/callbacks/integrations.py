"""Framework integrations for the bench step-timestamp logger.

Parity: /root/reference/sky/callbacks/sky_callback/integrations/
(Keras / PyTorch-Lightning / HuggingFace-Transformers callbacks that
drive base.on_step_begin/end from inside the user's training loop).
TPU-first additions: a JAX step-function wrapper (the idiomatic loop
here has no callback object) and lazy imports so none of the host
frameworks are required unless used.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

from skypilot_tpu.callbacks import base


def wrap_jax_step(step_fn: Callable[..., Any],
                  log_dir: Optional[str] = None,
                  total_steps: Optional[int] = None) -> Callable[..., Any]:
    """Wrap a (jitted) train-step callable so every invocation is
    timestamped:

        step_fn = integrations.wrap_jax_step(jit_train_step(...))
        for batch in data:
            state, metrics = step_fn(state, batch)

    Timing note: the wrapper brackets the DISPATCH of the step.  Under
    JAX's async dispatch consecutive step calls still measure true
    steady-state step time (each dispatch blocks once the pipeline is
    ~2 steps deep), matching how bench.py times the same loop.
    """
    cb = base.init(log_dir=log_dir, total_steps=total_steps)

    @functools.wraps(step_fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        with cb.step():
            return step_fn(*args, **kwargs)

    return wrapped


def transformers_callback(log_dir: Optional[str] = None):
    """A HuggingFace `transformers.TrainerCallback` that reports step
    timestamps (reference: integrations/transformers wrapper):

        trainer = Trainer(..., callbacks=[transformers_callback()])
    """
    from transformers import TrainerCallback  # pylint: disable=import-outside-toplevel

    class _SkyTpuTransformersCallback(TrainerCallback):

        def on_train_begin(self, args, state, control, **kwargs):
            del args, control, kwargs
            base.init(log_dir=log_dir, total_steps=state.max_steps or None)

        def on_step_begin(self, args, state, control, **kwargs):
            del args, state, control, kwargs
            base.on_step_begin()

        def on_step_end(self, args, state, control, **kwargs):
            del args, state, control, kwargs
            base.on_step_end()

    return _SkyTpuTransformersCallback()


def lightning_callback(log_dir: Optional[str] = None):
    """A pytorch_lightning.Callback reporting step timestamps."""
    import pytorch_lightning as pl  # pylint: disable=import-outside-toplevel

    class _SkyTpuLightningCallback(pl.Callback):

        def on_train_start(self, trainer, pl_module):
            del pl_module
            total = getattr(trainer, 'max_steps', None)
            base.init(log_dir=log_dir,
                      total_steps=total if total and total > 0 else None)

        def on_train_batch_start(self, *args: Any, **kwargs: Any):
            del args, kwargs
            base.on_step_begin()

        def on_train_batch_end(self, *args: Any, **kwargs: Any):
            del args, kwargs
            base.on_step_end()

    return _SkyTpuLightningCallback()


def keras_callback(log_dir: Optional[str] = None):
    """A tf.keras.callbacks.Callback reporting step timestamps."""
    from tensorflow import keras  # pylint: disable=import-outside-toplevel

    class _SkyTpuKerasCallback(keras.callbacks.Callback):

        def on_train_begin(self, logs=None):
            del logs
            base.init(log_dir=log_dir)

        def on_train_batch_begin(self, batch, logs=None):
            del batch, logs
            base.on_step_begin()

        def on_train_batch_end(self, batch, logs=None):
            del batch, logs
            base.on_step_end()

    return _SkyTpuKerasCallback()
