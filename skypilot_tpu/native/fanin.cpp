// skytpu-fanin: native gang process supervisor + log multiplexer.
//
// The hot loop of gang execution (SURVEY.md §7.9: "log-pipe fan-in"):
// spawn one command per slice host (the ssh client or local process),
// multiplex their interleaved output line-by-line onto stdout with
// "(rank N)" prefixes, tee each rank's raw stream to its own log file,
// and enforce all-or-nothing slice semantics — the first non-zero rank
// SIGTERMs every other rank's process group (escalating to SIGKILL),
// mirroring the reference's `get_or_fail` fan-in
// (/root/reference/sky/backends/cloud_vm_ray_backend.py:294-328) without
// a Ray dependency or per-rank Python threads.
//
// Spec file format (written by skypilot_tpu/native/__init__.py):
//   "SKYFANIN1\n<num_ranks>\n" followed, per rank, by NUL-delimited
//   fields: log_path NUL argc NUL arg0 NUL arg1 NUL ... argN NUL
//
// Final stdout line:  FANIN_EXIT {"0":rc0,"1":rc1,...}
// Exit status: 0 iff every rank exited 0.
#include <cerrno>
#include <cassert>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <poll.h>
#include <string>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

namespace {

struct Rank {
  std::string log_path;
  std::vector<std::string> argv;
  pid_t pid = -1;
  int pipe_fd = -1;
  int log_fd = -1;
  int exit_code = -1;   // -1: still running
  std::string linebuf;  // partial line accumulator
};

volatile sig_atomic_t g_got_signal = 0;

void signal_handler(int sig) { g_got_signal = sig; }

std::string read_file(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) {
    std::perror("fanin: open spec");
    std::exit(252);
  }
  std::string out;
  char buf[1 << 16];
  size_t n;
  while ((n = std::fread(buf, 1, sizeof(buf), f)) > 0) out.append(buf, n);
  std::fclose(f);
  return out;
}

std::vector<Rank> parse_spec(const std::string& data) {
  const char kHeader[] = "SKYFANIN1\n";
  if (data.rfind(kHeader, 0) != 0) {
    std::fprintf(stderr, "fanin: bad spec header\n");
    std::exit(252);
  }
  size_t pos = sizeof(kHeader) - 1;
  size_t eol = data.find('\n', pos);
  int num_ranks = std::atoi(data.substr(pos, eol - pos).c_str());
  pos = eol + 1;
  auto next_field = [&]() {
    size_t nul = data.find('\0', pos);
    assert(nul != std::string::npos);
    std::string field = data.substr(pos, nul - pos);
    pos = nul + 1;
    return field;
  };
  std::vector<Rank> ranks(num_ranks);
  for (auto& rank : ranks) {
    rank.log_path = next_field();
    int argc = std::atoi(next_field().c_str());
    for (int i = 0; i < argc; ++i) rank.argv.push_back(next_field());
  }
  return ranks;
}

void spawn(Rank& rank) {
  int fds[2];
  if (pipe(fds) != 0) {
    std::perror("fanin: pipe");
    std::exit(252);
  }
  pid_t pid = fork();
  if (pid < 0) {
    std::perror("fanin: fork");
    std::exit(252);
  }
  if (pid == 0) {
    // Child: own process group so the whole remote-driver tree (ssh or
    // bash) can be signalled as a unit.
    setpgid(0, 0);
    dup2(fds[1], STDOUT_FILENO);
    dup2(fds[1], STDERR_FILENO);
    close(fds[0]);
    close(fds[1]);
    std::vector<char*> argv;
    argv.reserve(rank.argv.size() + 1);
    for (auto& a : rank.argv) argv.push_back(const_cast<char*>(a.c_str()));
    argv.push_back(nullptr);
    execvp(argv[0], argv.data());
    std::fprintf(stderr, "fanin: execvp %s: %s\n", argv[0],
                 std::strerror(errno));
    _exit(253);
  }
  setpgid(pid, pid);  // also from parent: avoid the startup race
  close(fds[1]);
  fcntl(fds[0], F_SETFL, O_NONBLOCK);
  rank.pid = pid;
  rank.pipe_fd = fds[0];
  rank.log_fd = open(rank.log_path.c_str(),
                     O_WRONLY | O_CREAT | O_APPEND, 0644);
}

void emit_lines(Rank& rank, size_t idx, const char* buf, ssize_t n) {
  if (rank.log_fd >= 0) {
    ssize_t off = 0;
    while (off < n) {
      ssize_t w = write(rank.log_fd, buf + off, n - off);
      if (w <= 0) break;
      off += w;
    }
  }
  rank.linebuf.append(buf, n);
  size_t start = 0;
  for (;;) {
    size_t nl = rank.linebuf.find('\n', start);
    if (nl == std::string::npos) break;
    std::fprintf(stdout, "(rank %zu) %.*s\n", idx,
                 static_cast<int>(nl - start), rank.linebuf.data() + start);
    start = nl + 1;
  }
  rank.linebuf.erase(0, start);
  std::fflush(stdout);
}

void flush_tail(Rank& rank, size_t idx) {
  if (!rank.linebuf.empty()) {
    std::fprintf(stdout, "(rank %zu) %s\n", idx, rank.linebuf.c_str());
    rank.linebuf.clear();
    std::fflush(stdout);
  }
}

void kill_all(std::vector<Rank>& ranks, int sig) {
  for (auto& rank : ranks) {
    if (rank.pid > 0 && rank.exit_code < 0) kill(-rank.pid, sig);
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: fanin <specfile>\n");
    return 252;
  }
  std::signal(SIGTERM, signal_handler);
  std::signal(SIGINT, signal_handler);
  std::signal(SIGPIPE, SIG_IGN);

  std::vector<Rank> ranks = parse_spec(read_file(argv[1]));
  for (auto& rank : ranks) spawn(rank);

  size_t alive = ranks.size();
  bool failed = false;
  int grace_polls_left = -1;  // countdown to SIGKILL after fail-fast

  while (alive > 0) {
    if (g_got_signal != 0) {
      kill_all(ranks, SIGTERM);
      g_got_signal = 0;
      failed = true;
      grace_polls_left = 50;  // ~5s then SIGKILL
    }
    std::vector<pollfd> pfds;
    std::vector<size_t> owner;
    for (size_t i = 0; i < ranks.size(); ++i) {
      if (ranks[i].pipe_fd >= 0) {
        pfds.push_back({ranks[i].pipe_fd, POLLIN, 0});
        owner.push_back(i);
      }
    }
    if (!pfds.empty()) {
      int rv = poll(pfds.data(), pfds.size(), 100);
      if (rv > 0) {
        char buf[1 << 16];
        for (size_t p = 0; p < pfds.size(); ++p) {
          if (pfds[p].revents == 0) continue;
          Rank& rank = ranks[owner[p]];
          for (;;) {
            ssize_t n = read(rank.pipe_fd, buf, sizeof(buf));
            if (n > 0) {
              emit_lines(rank, owner[p], buf, n);
              continue;
            }
            if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
            // EOF or error: stream closed.
            flush_tail(rank, owner[p]);
            close(rank.pipe_fd);
            rank.pipe_fd = -1;
            break;
          }
        }
      }
    } else {
      // All pipes closed; children may still be exiting.
      usleep(50 * 1000);
    }
    // Reap exits.
    for (size_t i = 0; i < ranks.size(); ++i) {
      Rank& rank = ranks[i];
      if (rank.pid <= 0 || rank.exit_code >= 0) continue;
      int status = 0;
      pid_t r = waitpid(rank.pid, &status, WNOHANG);
      if (r == rank.pid) {
        rank.exit_code = WIFEXITED(status) ? WEXITSTATUS(status)
                         : 128 + WTERMSIG(status);
        --alive;
        if (rank.exit_code != 0 && !failed) {
          // All-or-nothing: first failure cancels the gang.
          failed = true;
          std::fprintf(stdout,
                       "(fanin) rank %zu exited %d; cancelling gang\n", i,
                       rank.exit_code);
          std::fflush(stdout);
          kill_all(ranks, SIGTERM);
          grace_polls_left = 50;
        }
      }
    }
    if (grace_polls_left > 0 && --grace_polls_left == 0) {
      kill_all(ranks, SIGKILL);
    }
  }

  // Drain any last buffered output, close logs.
  for (size_t i = 0; i < ranks.size(); ++i) {
    if (ranks[i].pipe_fd >= 0) {
      char buf[1 << 16];
      ssize_t n;
      while ((n = read(ranks[i].pipe_fd, buf, sizeof(buf))) > 0)
        emit_lines(ranks[i], i, buf, n);
      flush_tail(ranks[i], i);
      close(ranks[i].pipe_fd);
    }
    if (ranks[i].log_fd >= 0) close(ranks[i].log_fd);
  }

  std::string summary = "FANIN_EXIT {";
  bool ok = true;
  for (size_t i = 0; i < ranks.size(); ++i) {
    if (i != 0) summary += ",";
    summary += "\"" + std::to_string(i) +
               "\":" + std::to_string(ranks[i].exit_code);
    if (ranks[i].exit_code != 0) ok = false;
  }
  summary += "}";
  std::fprintf(stdout, "%s\n", summary.c_str());
  std::fflush(stdout);
  return ok ? 0 : 1;
}
