"""Native runtime components (C++), built on demand and cached.

The reference framework is pure Python with native work delegated to
Ray's C++ core (SURVEY.md §2.1).  Here the gang-exec hot path — N-host
process supervision + log fan-in — is a small C++ tool (fanin.cpp),
compiled once per source hash into SKYTPU_HOME/native/ and used by the
gang supervisor when available; callers fall back to the pure-Python
thread-pool path when no toolchain exists.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
from typing import Dict, List, Optional, Sequence

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)

_SOURCE = os.path.join(os.path.dirname(__file__), 'fanin.cpp')
ENV_DISABLE = 'SKYTPU_DISABLE_NATIVE_FANIN'


def _build_dir() -> str:
    return common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'native'))


def ensure_fanin_built() -> Optional[str]:
    """Compile (or reuse) the fanin binary; None when unavailable."""
    if os.environ.get(ENV_DISABLE) == '1':
        return None
    compiler = shutil.which('g++') or shutil.which('c++')
    if compiler is None:
        return None
    try:
        with open(_SOURCE, 'rb') as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    binary = os.path.join(_build_dir(), f'fanin-{digest}')
    if os.path.exists(binary):
        return binary
    # Unique tmp per process: concurrent gang supervisors may race to
    # build; os.replace makes the final install atomic either way.
    tmp = f'{binary}.{os.getpid()}.tmp'
    proc = subprocess.run(
        [compiler, '-O2', '-std=c++17', '-o', tmp, _SOURCE],
        capture_output=True, text=True, check=False)
    if proc.returncode != 0:
        logger.warning(f'native fanin build failed (falling back to '
                       f'python): {proc.stderr[-400:]}')
        return None
    os.replace(tmp, binary)
    logger.debug(f'built native fanin at {binary}')
    return binary


def write_spec(path: str, log_paths: Sequence[str],
               argvs: Sequence[Sequence[str]]) -> None:
    assert len(log_paths) == len(argvs)
    with open(path, 'wb') as f:
        f.write(f'SKYFANIN1\n{len(argvs)}\n'.encode())
        for log_path, argv in zip(log_paths, argvs):
            f.write(log_path.encode() + b'\0')
            f.write(str(len(argv)).encode() + b'\0')
            for arg in argv:
                f.write(arg.encode() + b'\0')


def run_fanin(binary: str, spec_path: str,
              env: Optional[Dict[str, str]] = None,
              cwd: Optional[str] = None) -> Dict[int, int]:
    """Run the gang; streams multiplexed output to our stdout.  Returns
    {rank: exit_code} parsed from the FANIN_EXIT trailer."""
    proc = subprocess.Popen(  # pylint: disable=consider-using-with
        [binary, spec_path], stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, errors='replace', env=env,
        cwd=cwd)
    returncodes: Dict[int, int] = {}
    assert proc.stdout is not None
    for line in proc.stdout:
        if line.startswith('FANIN_EXIT '):
            returncodes = {
                int(k): v
                for k, v in json.loads(line[len('FANIN_EXIT '):]).items()
            }
        else:
            print(line, end='', flush=True)
    proc.wait()
    return returncodes
