"""Logging setup.

Parity: /root/reference/sky/sky_logging.py:1-145 (env-tunable logger with a
single stream handler and a `silent` context). Simplified: one formatter, no
ray-specific line processors.
"""
from __future__ import annotations

import contextlib
import logging
import os
import sys
import threading

_FORMAT = '%(levelname).1s %(asctime)s %(filename)s:%(lineno)d] %(message)s'
_DATE_FORMAT = '%m-%d %H:%M:%S'

# Reentrant: _setup imports the structured-handler module while
# holding the lock, and that import chain may itself call init_logger
# (which re-enters _setup and returns on the already-set handler).
_lock = threading.RLock()
_root_logger = logging.getLogger('skypilot_tpu')
_default_handler: 'logging.Handler | None' = None
# The fleet log plane (observability/logs.py): every record also lands
# in the bounded structured ring behind `GET /logs`.
_structured_handler: 'logging.Handler | None' = None

# Thread-local silence flag, toggled by the `silent()` context manager.
_local = threading.local()


def _show_logging_prefix() -> bool:
    return os.environ.get('SKYTPU_DEBUG', '0') == '1'


class _FmtFilter(logging.Filter):

    def filter(self, record: logging.LogRecord) -> bool:
        return not getattr(_local, 'silent', False)


def _setup() -> None:
    global _default_handler, _structured_handler
    with _lock:
        if _default_handler is not None:
            return
        _default_handler = logging.StreamHandler(sys.stdout)
        _default_handler.setLevel(logging.DEBUG)
        fmt = _FORMAT if _show_logging_prefix() else '%(message)s'
        _default_handler.setFormatter(
            logging.Formatter(fmt, datefmt=_DATE_FORMAT))
        _default_handler.addFilter(_FmtFilter())
        _root_logger.addHandler(_default_handler)
        try:
            # Deferred import: the first init_logger call can arrive
            # while observability modules are themselves importing.
            from skypilot_tpu.observability import logs as _logs  # pylint: disable=import-outside-toplevel
            _structured_handler = _logs.StructuredLogHandler()
            _root_logger.addHandler(_structured_handler)
        except Exception:  # pylint: disable=broad-except
            _structured_handler = None  # never break logging itself
        level = logging.DEBUG if os.environ.get('SKYTPU_DEBUG') else logging.INFO
        _root_logger.setLevel(level)
        _root_logger.propagate = False


def init_logger(name: str) -> logging.Logger:
    _setup()
    return logging.getLogger(f'skypilot_tpu.{name}')


def reload_logger() -> None:
    """Re-create the handlers (e.g. after env flags change in tests)."""
    global _default_handler, _structured_handler
    with _lock:
        if _default_handler is not None:
            _root_logger.removeHandler(_default_handler)
            _default_handler = None
        if _structured_handler is not None:
            _root_logger.removeHandler(_structured_handler)
            _structured_handler = None
    _setup()


@contextlib.contextmanager
def silent():
    """Suppress all framework log output inside the context."""
    prev = getattr(_local, 'silent', False)
    _local.silent = True
    try:
        yield
    finally:
        _local.silent = prev


def is_silent() -> bool:
    return getattr(_local, 'silent', False)


def print_exception_no_traceback():
    """Context: raise with a clean one-line error (no traceback) in CLI paths."""
    return contextlib.nullcontext()
