"""Serve codegen: client↔controller-cluster RPC over ssh.

Parity: /root/reference/sky/serve/serve_utils.py ServeCodeGen — in
cluster mode the serve state db lives on the controller cluster;
status/down/endpoint queries route through generated one-liners
executed on its head, the same transport as jobs/utils.py.
"""
from __future__ import annotations

import shlex
from typing import Any, List, Optional

from skypilot_tpu.serve import constants as serve_constants
from skypilot_tpu.skylet import constants


class ServeCodeGen:

    _PREFIX = ('import json, os; '
               "os.environ.setdefault('PYTHONUNBUFFERED','1'); "
               f"os.environ['{serve_constants.ENV_ON_CONTROLLER}'] = '1'; "
               'from skypilot_tpu.serve import serve_state')

    @classmethod
    def _build(cls, code: List[str]) -> str:
        full = '; '.join([cls._PREFIX] + code)
        python = constants.SKY_PYTHON_CMD
        app_dir = constants.SKY_REMOTE_APP_DIR
        return (f'PYTHONPATH={app_dir}:$PYTHONPATH {python} -u -c '
                f'{shlex.quote(full)}')

    @classmethod
    def status(cls, service_names: Optional[List[str]]) -> str:
        return cls._build([
            'from skypilot_tpu.serve import core',
            f'records = core.status({service_names!r})',
            'print("SERVE_STATUS:" + json.dumps(records), flush=True)',
        ])

    @classmethod
    def get_service(cls, service_name: str) -> str:
        return cls._build([
            f'record = serve_state.get_service({service_name!r})',
            'print("SERVE_RECORD:" + json.dumps(record), flush=True)',
        ])

    @classmethod
    def down(cls, service_name: str, purge: bool) -> str:
        return cls._build([
            'from skypilot_tpu.serve import core',
            f'core.down({service_name!r}, purge={purge})',
            'print("SERVE_DOWN:" + json.dumps(True), flush=True)',
        ])

    @classmethod
    def update(cls, service_name: str, remote_yaml: str) -> str:
        return cls._build([
            'from skypilot_tpu import task as task_lib',
            'from skypilot_tpu.serve import core',
            f'task = task_lib.Task.from_yaml('
            f'os.path.expanduser({remote_yaml!r}))',
            f'version = core.update(task, {service_name!r})',
            'print("SERVE_VERSION:" + json.dumps(version), flush=True)',
        ])


def run_on_serve_controller(code: str, tag: str) -> Any:
    """Execute codegen on the serve controller cluster's head; parse
    the tagged JSON line."""
    from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.skylet import job_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import subprocess_utils  # pylint: disable=import-outside-toplevel
    handle = backend_utils.check_cluster_available(
        serve_constants.CONTROLLER_CLUSTER_NAME)
    head = handle.get_command_runners()[0]
    rc, stdout, stderr = head.run(code, require_outputs=True,
                                  stream_logs=False)
    subprocess_utils.handle_returncode(
        rc, code, 'Failed to reach the serve controller cluster.', stderr)
    return job_lib.parse_tagged_json(stdout, tag)


def run_if_controller_exists(code: str, tag: str) -> Any:
    """Like run_on_serve_controller but returns None when the
    controller cluster does not exist yet (first `serve up`).

    An EXISTING-but-unreachable controller raises — conflating the two
    would let `serve up` double-start a daemon and `serve status`
    report 'no services' while replicas keep running."""
    from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
    record = global_user_state.get_cluster_from_name(
        serve_constants.CONTROLLER_CLUSTER_NAME)
    if record is None:
        return None
    return run_on_serve_controller(code, tag)


def controller_head_ip() -> str:
    from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
    handle = backend_utils.check_cluster_available(
        serve_constants.CONTROLLER_CLUSTER_NAME)
    ips = handle.external_ips()
    return ips[0] if ips else '127.0.0.1'


def controller_mode() -> str:
    import os  # pylint: disable=import-outside-toplevel

    from skypilot_tpu import config as config_lib  # pylint: disable=import-outside-toplevel
    if os.environ.get(serve_constants.ENV_ON_CONTROLLER) == '1':
        # On the controller itself, every operation is local.
        return 'process'
    return config_lib.get_nested(serve_constants.CONTROLLER_MODE_KEY,
                                 serve_constants.DEFAULT_CONTROLLER_MODE)
