"""Canonical cross-process HTTP protocol: every header and endpoint
path the serving fleet's processes speak to each other.

The fleet is a multi-process distributed system — two replica HTTP
fronts (serve/model_server.py threaded, serve/async_server.py asyncio),
the load balancer's `/lb/` control plane, and the controller's
`/controller/` endpoint — and the contracts BETWEEN them (which paths
exist, which headers are stamped and read) used to live as ~30
scattered string literals.  This module is the single home for those
literals; `sky lint`'s http-contract pass (analysis/passes/
http_contract.py) forbids new raw `X-SkyTPU-*` header or endpoint-path
literals anywhere else in the package and cross-checks client call
sites against registered routes.

Import direction: this module imports nothing from the package, so
every layer (router, tracing, servers, CLI) can depend on it.
`serve/router.py` and `observability/tracing.py` re-export the header
names they historically owned — existing importers keep working.
"""
from __future__ import annotations

# --------------------------------------------------------------- headers
# Propagated load_balancer -> model_server/async_server -> engine slot;
# servers echo it on the response so clients can correlate.
REQUEST_ID_HEADER = 'X-SkyTPU-Request-Id'
# Routing metadata the LB forwards to the replica (and the replica
# stamps into the request's span): which role pool served the request,
# whether prefix affinity hit, and how long the KV handoff took.
ROUTED_ROLE_HEADER = 'X-SkyTPU-Routed-Role'
AFFINITY_HEADER = 'X-SkyTPU-Affinity'
HANDOFF_MS_HEADER = 'X-SkyTPU-Handoff-Ms'
# Which LB delivery attempt this is (0 = first try, 1 = the one-shot
# same-role retry).  The retry reuses the request id on a SECOND
# replica; the attempt tag keeps the two processes' span segments
# distinct when `sky serve trace` stitches them.
ATTEMPT_HEADER = 'X-SkyTPU-Attempt'
# Per-request time budget in milliseconds; propagated LB -> server ->
# engine slot.  Past it, the request is reaped and its KV pages freed
# (HTTP 504) instead of decoding to a client that stopped waiting.
DEADLINE_HEADER = 'X-SkyTPU-Deadline-Ms'
# QoS priority class ('interactive' | 'batch').  Clients may set it;
# the router stamps the default class when absent, applies weighted
# admission per class, and the engine scheduler enforces the class's
# token budget and deadline default.
QOS_CLASS_HEADER = 'X-SkyTPU-QoS-Class'

HEADERS = (REQUEST_ID_HEADER, ROUTED_ROLE_HEADER, AFFINITY_HEADER,
           HANDOFF_MS_HEADER, ATTEMPT_HEADER, DEADLINE_HEADER,
           QOS_CLASS_HEADER)

# --------------------------------------------- replica front (both HTTP
# fronts expose the identical surface; the http-contract pass proves it)
METRICS = '/metrics'                  # GET: Prometheus exposition
SPANS = '/spans'                      # GET: trace-segment export
GENERATE = '/generate'                # POST: batch token generation
GENERATE_STREAM = '/generate_stream'  # POST: SSE token stream
GENERATE_TEXT = '/generate_text'      # POST: text in/out (tokenizer)
PREFILL_EXPORT = '/prefill_export'    # POST: KV handoff, prefill side
KV_IMPORT = '/kv_import'              # POST: KV handoff, decode side
DRAIN = '/drain'                      # POST: controller retirement path
PREFIX_EXPORT = '/prefix_export'      # POST: drain-time sibling handoff
ROLE_BUDGET = '/role_budget'          # POST: rebalance push / role morph
WEIGHTS_SWAP = '/weights_swap'        # POST: live checkpoint swap
PROFILE = '/profile'                  # GET: tick-phase profiling ring
LOGS = '/logs'                        # GET: structured log-ring export
# Any other GET answers the health/readiness payload (the probe path).

REPLICA_PATHS = (METRICS, SPANS, GENERATE, GENERATE_STREAM,
                 GENERATE_TEXT, PREFILL_EXPORT, KV_IMPORT, DRAIN,
                 PREFIX_EXPORT, ROLE_BUDGET, WEIGHTS_SWAP, PROFILE,
                 LOGS)

# ------------------------------------------------- LB control plane (the
# `/lb/` prefix is never proxied; the LB answers these itself)
LB_PREFIX = '/lb/'
LB_RETIRE = '/lb/retire'              # POST: controller's drain nudge
LB_METRICS = '/lb/metrics'            # GET: LB process exposition
LB_SPANS = '/lb/spans'                # GET: LB trace segments
# Router-tier brain replication: the controller pushes ready/retired
# deltas here (fan-out to every router instance), and sibling routers
# replicate retire/affinity deltas peer-to-peer so a prefix pinned on
# one instance re-homes identically on all of them.
LB_STATE = '/lb/state'                # POST: ready/retired/affinity deltas
LB_LOGS = '/lb/logs'                  # GET: LB structured log ring

LB_PATHS = (LB_RETIRE, LB_METRICS, LB_SPANS, LB_STATE, LB_LOGS)

# ------------------------------------------------------------ controller
CONTROLLER_PREFIX = '/controller/'
CONTROLLER_SYNC = '/controller/load_balancer_sync'   # GET+POST
CONTROLLER_TELEMETRY = '/controller/telemetry'       # GET: serve top
CONTROLLER_UPDATE = '/controller/update_service'     # POST
CONTROLLER_TERMINATE = '/controller/terminate'       # POST
CONTROLLER_LOGS = '/controller/logs'                 # GET: log ring

CONTROLLER_PATHS = (CONTROLLER_SYNC, CONTROLLER_TELEMETRY,
                    CONTROLLER_UPDATE, CONTROLLER_TERMINATE,
                    CONTROLLER_LOGS)

PATHS = REPLICA_PATHS + LB_PATHS + CONTROLLER_PATHS
