"""Serve constants: controller placement config.

Parity: /root/reference/sky/serve/constants.py + serve/core.py:203 (the
reference ALWAYS places the serve controller on a provisioned VM; here
placement is configurable like managed jobs'):

- 'process' (default): controller + LB run as a detached local daemon.
- 'cluster': a controller cluster is launched through the normal stack
  and runs the identical service daemon (reference behavior); client
  queries route there over ssh codegen (serve/utils.py ServeCodeGen).
"""
from __future__ import annotations

CONTROLLER_MODE_KEY = ('serve', 'controller', 'mode')
DEFAULT_CONTROLLER_MODE = 'process'
# One shared controller cluster hosts every service's daemon (parity:
# the reference multiplexes services onto one controller VM).
CONTROLLER_CLUSTER_NAME = 'skytpu-serve-controller'
ENV_ON_CONTROLLER = 'SKYTPU_ON_CONTROLLER'
