"""QoS priority classes for the serving front door.

Two classes — ``interactive`` (chat traffic: low latency, small token
budgets, tight deadlines) and ``batch`` (offline inference: throughput,
big budgets, loose deadlines) — ride every request as the
``X-SkyTPU-QoS-Class`` header (serve/http_protocol.py).  Enforcement is
split across the two layers that can act on it:

- **Router (weighted admission).**  When a router instance is near its
  in-flight cap (``SKYTPU_LB_QOS_MAX_INFLIGHT`` or the service spec's
  ``routers.qos``), each class is admitted up to its weighted share of
  the cap; beyond it the request is shed with 429 + Retry-After.  The
  weights guarantee interactive traffic a floor under a batch flood —
  and a batch floor under an interactive flood (no starvation either
  way; the ``qos_fairness`` invariant replays the journal to prove
  it).
- **Engine scheduler (budgets + deadlines).**  The admission queue
  clamps each request's ``max_new_tokens`` to its class budget and
  applies the class deadline default when the request carries none,
  and pops queued work in smooth-weighted class order.

Config precedence: the service spec's ``routers: {qos: {...}}`` block
(pushed by the controller / exported as ``SKYTPU_QOS_SPEC`` to
replicas) over the env defaults over the built-ins.
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, Optional

CLASSES = ('interactive', 'batch')
INTERACTIVE = 'interactive'
BATCH = 'batch'

_DEFAULT_WEIGHTS = {INTERACTIVE: 4, BATCH: 1}


def default_class() -> str:
    """Class assumed when a request carries no QoS header."""
    value = os.environ.get('SKYTPU_QOS_DEFAULT_CLASS', INTERACTIVE)
    return value if value in CLASSES else INTERACTIVE


def normalize(value: Optional[str]) -> str:
    """Clamp an arbitrary header value to a known class."""
    if value:
        value = value.strip().lower()
        if value in CLASSES:
            return value
    return default_class()


@dataclasses.dataclass
class QosClassSpec:
    """Per-class policy knobs."""
    weight: int = 1                       # admission share
    max_new_tokens: Optional[int] = None  # token budget (clamp)
    deadline_ms: Optional[float] = None   # deadline default

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {'weight': self.weight}
        if self.max_new_tokens is not None:
            out['max_new_tokens'] = self.max_new_tokens
        if self.deadline_ms is not None:
            out['deadline_ms'] = self.deadline_ms
        return out


def _env_weights() -> Dict[str, int]:
    """SKYTPU_LB_QOS_WEIGHTS, e.g. 'interactive=4,batch=1'."""
    raw = os.environ.get('SKYTPU_LB_QOS_WEIGHTS', '')
    weights = dict(_DEFAULT_WEIGHTS)
    for part in raw.split(','):
        name, _, value = part.partition('=')
        name = name.strip().lower()
        if name in CLASSES:
            try:
                weights[name] = max(1, int(value))
            except ValueError:
                pass
    return weights


def from_config(config: Optional[Dict[str, Any]]
                ) -> Dict[str, QosClassSpec]:
    """Class specs from a ``routers.qos`` block (service_spec already
    validated the keys); falls back to env/built-in defaults per
    class."""
    weights = _env_weights()
    specs = {name: QosClassSpec(weight=weights[name])
             for name in CLASSES}
    for name, cfg in (config or {}).items():
        if name not in CLASSES or not isinstance(cfg, dict):
            continue
        spec = specs[name]
        if cfg.get('weight') is not None:
            spec.weight = max(1, int(cfg['weight']))
        if cfg.get('max_new_tokens') is not None:
            spec.max_new_tokens = int(cfg['max_new_tokens'])
        if cfg.get('deadline_ms') is not None:
            spec.deadline_ms = float(cfg['deadline_ms'])
    return specs


# engine_config is on the per-request path; cache keyed by the raw env
# strings so a changed env (tests) invalidates, steady state parses once.
_ENGINE_CACHE: Dict[Any, Dict[str, QosClassSpec]] = {}


def engine_config() -> Dict[str, QosClassSpec]:
    """Class specs for the engine scheduler, from SKYTPU_QOS_SPEC (the
    controller exports the spec's ``routers.qos`` block as JSON when it
    launches replicas)."""
    cache_key = (os.environ.get('SKYTPU_QOS_SPEC'),
                 os.environ.get('SKYTPU_LB_QOS_WEIGHTS'))
    cached = _ENGINE_CACHE.get(cache_key)
    if cached is not None:
        return cached
    raw = cache_key[0]
    config = None
    if raw:
        try:
            config = json.loads(raw)
        except json.JSONDecodeError:
            config = None
    specs = from_config(config if isinstance(config, dict) else None)
    _ENGINE_CACHE.clear()
    _ENGINE_CACHE[cache_key] = specs
    return specs


def admission_limits(max_inflight: Optional[int],
                     specs: Dict[str, QosClassSpec]
                     ) -> Dict[str, Optional[int]]:
    """Per-class in-flight caps: each class gets at least its weighted
    share of the total cap (ceil, so small caps never round a class to
    zero).  None cap = unlimited (weighted admission disarmed)."""
    if not max_inflight or max_inflight <= 0:
        return {name: None for name in specs}
    total = sum(s.weight for s in specs.values()) or 1
    return {name: max(1, math.ceil(max_inflight * s.weight / total))
            for name, s in specs.items()}


def router_max_inflight() -> Optional[int]:
    """Router-instance in-flight cap arming weighted admission (unset
    or 0 = unlimited)."""
    try:
        value = int(os.environ.get('SKYTPU_LB_QOS_MAX_INFLIGHT', '0'))
    except ValueError:
        return None
    return value if value > 0 else None


def queue_wait_p50(hist: Optional[Dict[str, Any]]) -> Optional[float]:
    """Median queue wait in SECONDS from an engine's queue-wait
    histogram (scheduler.AdmissionQueue.stats()['queue_wait_hist'],
    bucket labels like ``'<0.5s'`` / ``'>=5.0s'``).

    Returns the upper bound of the first bucket whose cumulative count
    reaches half the total — a conservative (upper) median estimate,
    which is what the router's shed path wants for Retry-After: batch
    clients back off at least as long as the median admitted request
    waited.  None when the histogram is missing, empty, or malformed
    (callers fall back to the static default)."""
    if not isinstance(hist, dict) or not hist:
        return None
    buckets = []
    overflow = 0
    try:
        for label, count in hist.items():
            count = int(count)
            if count < 0:
                return None
            if label.startswith('<'):
                buckets.append((float(label[1:].rstrip('s')), count))
            elif label.startswith('>='):
                overflow += count
            else:
                return None
    except (ValueError, AttributeError, TypeError):
        return None
    buckets.sort()
    total = sum(c for _, c in buckets) + overflow
    if total <= 0:
        return None
    half = total / 2.0
    cumulative = 0
    for upper, count in buckets:
        cumulative += count
        if cumulative >= half:
            return upper
    # Median sits in the open-ended bucket: its lower bound is the
    # best defensible estimate (the largest finite bucket edge).
    return buckets[-1][0] if buckets else None


def validate_config(config: Any, where: str) -> None:
    """Spec-time validation for a ``qos:`` block (service_spec calls
    this; raising ValueError surfaces as InvalidTaskError there)."""
    if config is None:
        return
    if not isinstance(config, dict):
        raise ValueError(f'{where}: expected a mapping of QoS classes, '
                         f'got {type(config).__name__}')
    for name, cfg in config.items():
        if name not in CLASSES:
            raise ValueError(f'{where}: unknown QoS class {name!r}; '
                             f'one of {CLASSES}')
        if not isinstance(cfg, dict):
            raise ValueError(f'{where}.{name}: expected a mapping')
        for key in cfg:
            if key not in ('weight', 'max_new_tokens', 'deadline_ms'):
                raise ValueError(
                    f'{where}.{name}: unknown key {key!r}; one of '
                    f"('weight', 'max_new_tokens', 'deadline_ms')")
        if cfg.get('weight') is not None and int(cfg['weight']) < 1:
            raise ValueError(f'{where}.{name}.weight must be >= 1')
        if (cfg.get('max_new_tokens') is not None and
                int(cfg['max_new_tokens']) < 1):
            raise ValueError(
                f'{where}.{name}.max_new_tokens must be >= 1')
        if (cfg.get('deadline_ms') is not None and
                float(cfg['deadline_ms']) <= 0):
            raise ValueError(f'{where}.{name}.deadline_ms must be > 0')
