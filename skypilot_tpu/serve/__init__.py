"""SkyServe-equivalent: autoscaled serving on TPU slices.

Parity: /root/reference/sky/serve/ (controller, load balancer, replica
manager, autoscalers, service spec) — replicas are slice-clusters
launched through the normal stack; the control plane (controller + LB)
runs as local daemon processes or on a controller cluster, mirroring
the reference's controller-VM design (serve/service.py).
"""
from skypilot_tpu.serve.core import down
from skypilot_tpu.serve.core import status
from skypilot_tpu.serve.core import tail_logs
from skypilot_tpu.serve.core import up
from skypilot_tpu.serve.core import update
from skypilot_tpu.serve.service_spec import SkyServiceSpec

__all__ = ['SkyServiceSpec', 'down', 'status', 'tail_logs', 'up',
           'update']
