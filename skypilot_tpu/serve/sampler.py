"""Per-request sampling plumbing for the batching engine.

Split out of `serve/batching_engine.py` (the facade re-exports what
callers need): submit-side validation of sampling parameters against
the engine's compiled limits, and the jitted host->device staging that
flips a slot live — token selection itself runs ON DEVICE inside the
engine tick (`models/decode.batched_sample`), so this module is the
thin, recompile-safe edge around it:

- temperature is TRACED (client floats must not trigger a compile
  storm); top_k rides a static `max_top_k` table, so requested values
  are validated here against the engine's compiled ceiling;
- a request's stop set becomes a fixed-width, -1-padded device row
  (`max_stop_ids` wide — the multi-EOS stop sets of instruct
  checkpoints);
- `admit_state` writes a whole slot admission in ONE jitted dispatch
  instead of seven eager scatters on the hot path;
- :class:`NgramDrafter` — the per-slot host-side draft proposer for
  self-speculative decoding (lives next to the sampling state it
  shares a slot with; the engine verifies its drafts on device in one
  batched tick).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Iterable, List, Optional, Tuple


class NgramDrafter:
    """Prompt-lookup / n-gram draft proposer for self-speculative
    decoding: model-free and host-side.

    The draft for the next k tokens is the continuation of the most
    recent EARLIER occurrence of the current tail n-gram in the
    request's (prompt + generated) history, longest n first (n down
    from `max_ngram`).  Repetitive text — code, templated JSON,
    retrieval quotes, degenerate greedy cycles — makes these drafts
    mostly right, collapsing ITL by the acceptance length; random text
    makes them mostly wrong, which costs nothing beyond the
    already-batched verify tick.  Misses pad with the request's last
    token: pads must be VALID vocab ids because the verify forward
    embeds them before rejecting them.
    """

    def __init__(self, prompt_ids: Iterable[int], *,
                 max_ngram: int = 3) -> None:
        self.history: List[int] = [int(t) for t in prompt_ids]
        self.max_ngram = int(max_ngram)

    def observe(self, tokens: Iterable[int]) -> None:
        """Record tokens the engine actually emitted for this slot."""
        self.history.extend(int(t) for t in tokens)

    def propose(self, k: int) -> List[int]:
        """k draft tokens continuing the current history."""
        hist = self.history
        out: List[int] = []
        for n in range(min(self.max_ngram, len(hist) - 1), 0, -1):
            tail = hist[-n:]
            for i in range(len(hist) - n - 1, -1, -1):
                if hist[i:i + n] == tail:
                    out = hist[i + n:i + n + k]
                    break
            if out:
                break
        pad = hist[-1] if hist else 0
        out = out[:k]
        out.extend([pad] * (k - len(out)))
        return out


def validate_sampling(sampling: Optional[Any], *, max_top_k: int,
                      pipelined: bool) -> Tuple[float, int, int]:
    """-> (temperature, top_k, seed), raising ValueError on parameters
    the engine's compiled graphs cannot honor."""
    temperature, top_k, seed = 0.0, 0, 0
    if sampling is not None:
        temperature = float(sampling.temperature)
        top_k = int(sampling.top_k)
        seed = int(getattr(sampling, 'seed', 0))
    if top_k > max_top_k:
        raise ValueError(
            f'top_k {top_k} > engine max_top_k {max_top_k}')
    if temperature > 0.0 and not pipelined:
        raise ValueError(
            'the legacy (pipelined=False) loop serves greedy '
            'decoding only')
    return temperature, top_k, seed


def validate_stop_ids(stop_ids: Iterable[int],
                      max_stop_ids: int) -> None:
    n = len(tuple(stop_ids))
    if n > max_stop_ids:
        raise ValueError(
            f'{n} stop ids > engine max_stop_ids {max_stop_ids}')


class SlotSampler:
    """Jitted per-slot sampling/admission helpers bound to one engine
    configuration (max_top_k shapes the on-device top-k table;
    max_stop_ids the stop rows)."""

    def __init__(self, max_top_k: int, max_stop_ids: int) -> None:
        import jax

        from skypilot_tpu.models import decode

        self.max_top_k = int(max_top_k)
        self.max_stop_ids = int(max_stop_ids)
        self._jax = jax
        # One dispatch per admission for the whole per-slot state write
        # (NOT donated: the previous tick's token buffer may still be
        # pending its one-tick-behind host read).
        self._admit_state = jax.jit(decode.admit_slot_state)
        self._sample_one = jax.jit(
            functools.partial(decode.batched_sample,
                              max_top_k=self.max_top_k))

    def key(self, seed: int):
        return self._jax.random.PRNGKey(seed)

    def sample_one(self, logits, key, temperature: float,
                   top_k: int) -> int:
        """Select one token from single-row logits with the same math
        a tick uses (MoE first-token-from-prefill path)."""
        import jax.numpy as jnp
        return int(self._sample_one(
            logits, key[None],
            jnp.asarray([temperature], jnp.float32),
            jnp.asarray([top_k], jnp.int32))[0])

    def stop_row(self, stop_ids: Iterable[int]):
        row = [-1] * self.max_stop_ids
        for i, sid in enumerate(sorted(stop_ids)):
            row[i] = sid
        return row

    def admit(self, state: Dict[str, Any], slot_id: int, token: int,
              remaining: int, stop_ids: Iterable[int], key,
              temperature: float, top_k: int) -> Dict[str, Any]:
        """Flip a slot live in the device state (one jitted dispatch)."""
        import jax.numpy as jnp
        return self._admit_state(
            state, slot_id, token, remaining,
            jnp.asarray(self.stop_row(stop_ids), jnp.int32), key,
            temperature, top_k)
