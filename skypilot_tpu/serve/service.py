"""Service daemon entrypoint: one process running controller + LB.

Parity: /root/reference/sky/serve/service.py (spawns the
SkyServeController and SkyServeLoadBalancer for one service).

    python -m skypilot_tpu.serve.service --service-name NAME
"""
from __future__ import annotations

import argparse

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import serve_state

logger = sky_logging.init_logger(__name__)


def run_service(service_name: str, lb_port: int = 0) -> None:
    controller = controller_lib.SkyServeController(service_name)
    controller_port = controller.start_http()
    lb = lb_lib.SkyServeLoadBalancer(
        f'http://127.0.0.1:{controller_port}', port=lb_port)
    bound_lb_port = lb.start()
    serve_state.set_service_ports(service_name, controller_port,
                                  bound_lb_port)
    try:
        controller.run_loop()
    finally:
        lb.stop()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service-name', required=True)
    parser.add_argument('--lb-port', type=int, default=0)
    args = parser.parse_args()
    run_service(args.service_name, args.lb_port)


if __name__ == '__main__':
    main()
