"""Service daemon entrypoint: one process running controller + LB.

Parity: /root/reference/sky/serve/service.py (spawns the
SkyServeController and SkyServeLoadBalancer for one service).

    python -m skypilot_tpu.serve.service --service-name NAME
"""
from __future__ import annotations

import argparse

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import serve_state

logger = sky_logging.init_logger(__name__)


def run_service(service_name: str, lb_port: int = 0) -> None:
    import os  # pylint: disable=import-outside-toplevel
    controller = controller_lib.SkyServeController(service_name)
    controller_port = controller.start_http()
    lb = lb_lib.SkyServeLoadBalancer(
        f'http://127.0.0.1:{controller_port}', port=lb_port,
        policy=lb_lib.make_policy(
            getattr(controller.spec, 'load_balancing_policy', None)))
    bound_lb_port = lb.start()
    serve_state.set_service_ports(service_name, controller_port,
                                  bound_lb_port)
    # Record our own pid so `down` can terminate the daemon even when
    # it was started by a job supervisor on a controller cluster (in
    # process mode the parent overwrites this with the same value).
    serve_state.set_service_pids(service_name, controller_pid=os.getpid(),
                                 lb_pid=os.getpid())
    # Crash recovery: a restarted daemon re-adopts the live fleet from
    # serve_state (probing recorded URLs), resumes interrupted drains,
    # and warm-starts the autoscalers at the live count — the first
    # reconcile pass must not churn replicas that kept serving while
    # the control plane was down.
    controller.recover_fleet()
    try:
        controller.run_loop()
    finally:
        lb.stop()


def register_from_yaml(service_name: str, task_yaml: str) -> None:
    """Idempotently add the service record to the LOCAL state db.

    Cluster mode ships only the task YAML to the controller cluster;
    the daemon registers the service into the controller-side sqlite
    before starting (parity: reference serve/service.py loads the spec
    from the mounted service dir)."""
    import os  # pylint: disable=import-outside-toplevel

    from skypilot_tpu import task as task_lib  # pylint: disable=import-outside-toplevel
    if serve_state.get_service(service_name) is not None:
        return
    task_yaml = os.path.expanduser(task_yaml)
    task = task_lib.Task.from_yaml(task_yaml)
    if task.service is None:
        raise ValueError(f'{task_yaml} has no `service:` section.')
    serve_state.add_service(service_name,
                            task.service.to_yaml_config(), task_yaml)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service-name', required=True)
    parser.add_argument('--lb-port', type=int, default=0)
    parser.add_argument('--register-from-yaml', default=None,
                        help='Task YAML to register before serving '
                             '(controller-cluster mode).')
    args = parser.parse_args()
    if args.register_from_yaml:
        register_from_yaml(args.service_name, args.register_from_yaml)
    run_service(args.service_name, args.lb_port)


if __name__ == '__main__':
    main()
