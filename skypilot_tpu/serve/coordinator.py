"""Slice-replica rank protocol: rank 0 drives, followers execute in
lockstep.

A multi-host serving replica (serve/slice_replica.py) is a GANG: every
host runs the same SPMD program over the slice mesh, and the jitted
engine tick only completes when every host dispatches it.  The device
side is SPMD (XLA's collectives synchronize the chips); this module
owns the HOST side — the scheduling decisions rank 0 makes (admit this
request into that slot, run a tick, release a slot, shut down) must
reach every rank so all hosts dispatch IDENTICAL jitted calls in the
same order.  That is a classic replicated command log:

    rank 0 (SliceCoordinator)          rank 1..N-1 (followers)
      broadcast(cmd seq=k)  ───────▶     execute(cmd), ack(seq=k)
      wait for all acks      ◀───────     (dead rank = no ack)

Two follower transports:

- :class:`LocalRank` — an in-process emulated host (one thread + one
  queue per rank).  This is the tier-1 test mode: each emulated host
  owns one virtual device of the slice mesh, rank 0's dispatch covers
  all of them, and the followers execute the command log (and its
  chaos site) without duplicating device work.
- :class:`TcpRank` / :func:`follower_serve` — JSON-lines over TCP for
  REAL multi-host slices: each TPU-VM worker runs `python -m
  skypilot_tpu.serve.slice_replica` under the gang supervisor; rank 0
  binds the coordinator port from the gang env contract and ranks > 0
  connect and execute (their executor dispatches the same jitted step
  against their local devices).

Failure semantics: a slice fails AS A UNIT.  Any follower that raises
(chaos site ``serve.rank_exec``), disconnects, or misses the ack
deadline marks the rank DEAD; the next `tick()` on rank 0 raises
:class:`RankDead`, the engine fails everything in flight, `/health`
turns 503 with ``slice.degraded``, and the controller retires the
replica and launches a replacement (serve/replica_managers.py).  There
is no per-rank recovery — re-meshing a half-dead slice under live
traffic is strictly worse than rebuilding it behind the LB, which
keeps routing to the surviving replicas meanwhile.
"""
from __future__ import annotations

import dataclasses
import json
import queue
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.observability import logs as logs_lib
from skypilot_tpu.observability import metrics as metrics_lib

logger = sky_logging.init_logger(__name__)

# Per-rank tick executions, the "is every host keeping up" counter the
# `serve status --metrics` HOSTS column is backed by.
_M_RANK_TICKS = metrics_lib.counter(
    'skytpu_slice_rank_ticks_total',
    'Coordinated commands executed per slice rank.', ('rank',))
_M_RANK_DEATHS = metrics_lib.counter(
    'skytpu_slice_rank_deaths_total',
    'Slice ranks that died (raise/disconnect/ack timeout).', ('rank',))
_M_RANKS_ALIVE = metrics_lib.gauge(
    'skytpu_slice_ranks_alive',
    'Live ranks of the most recently constructed slice replica '
    '(including rank 0).')
_M_SYNC_SECONDS = metrics_lib.histogram(
    'skytpu_slice_sync_seconds',
    'Wall time per coordinated broadcast until every rank acked '
    '(the host-side slice synchronization overhead per tick).',
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.5))

# Command vocabulary.  ADMIT/RELEASE carry enough payload for a real
# follower to mirror rank 0's host-side bookkeeping; TICK is the hot
# one (one per engine tick).
CMD_TICK = 'tick'
CMD_ADMIT = 'admit'
CMD_PREFILL = 'prefill'
CMD_RELEASE = 'release'
CMD_SHUTDOWN = 'shutdown'

_ACK_TIMEOUT_S = 30.0


class RankDead(RuntimeError):
    """A slice rank died; the replica must fail as a unit."""

    def __init__(self, rank: int, reason: str) -> None:
        super().__init__(f'slice rank {rank} died: {reason}')
        self.rank = rank
        self.reason = reason


@dataclasses.dataclass
class Command:
    kind: str
    seq: int
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({'kind': self.kind, 'seq': self.seq,
                           'payload': self.payload})

    @classmethod
    def from_json(cls, line: str) -> 'Command':
        data = json.loads(line)
        return cls(kind=str(data['kind']), seq=int(data['seq']),
                   payload=dict(data.get('payload') or {}))


def _execute(rank: int, cmd: Command,
             executor: Optional[Callable[[Command], None]]) -> None:
    """One follower-side command execution — THE chaos boundary.

    `serve.rank_exec`: a raise here is this rank's host process dying
    mid-command (OOM, kernel panic, eviction); the coordinator sees a
    missing/failed ack and the slice degrades as a unit."""
    chaos_injector.inject('serve.rank_exec', rank=rank, command=cmd.kind)
    if executor is not None:
        rid = cmd.payload.get('request_id') if cmd.payload else None
        if rid is not None:
            # ADMIT replays carry the originating request id — bind it
            # so follower-rank log lines correlate in `serve logs`.
            with logs_lib.bind(request_id=str(rid)):
                executor(cmd)
        else:
            executor(cmd)


class RankChannel:
    """One follower as rank 0 sees it."""

    rank: int

    def send(self, cmd: Command) -> None:
        raise NotImplementedError

    def wait_ack(self, seq: int, timeout: float) -> None:
        """Blocks until the follower acked `seq`; raises RankDead."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalRank(RankChannel):
    """In-process emulated host: a daemon thread executing the command
    log.  The EMULATION contract: the rank's device work is already
    covered by rank 0's dispatch over the slice mesh (all virtual
    devices live in this process), so the executor defaults to a no-op
    — what runs here is the protocol itself: ordering, acks, the chaos
    site, and death semantics."""

    def __init__(self, rank: int,
                 executor: Optional[Callable[[Command], None]] = None
                 ) -> None:
        self.rank = rank
        self._executor = executor
        self._inbox: 'queue.Queue[Optional[Command]]' = queue.Queue()
        self._acked = -1
        self._dead: Optional[str] = None
        self._cond = threading.Condition()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f'slice-rank-{rank}')
        self._thread.start()

    def _run(self) -> None:
        while True:
            cmd = self._inbox.get()
            if cmd is None:
                return
            try:
                _execute(self.rank, cmd, self._executor)
            except Exception as e:  # pylint: disable=broad-except
                with self._cond:
                    self._dead = f'{type(e).__name__}: {e}'
                    self._cond.notify_all()
                return
            _M_RANK_TICKS.labels(rank=str(self.rank)).inc()
            with self._cond:
                self._acked = cmd.seq
                self._cond.notify_all()

    def send(self, cmd: Command) -> None:
        self._inbox.put(cmd)

    def wait_ack(self, seq: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._acked < seq:
                if self._dead is not None:
                    raise RankDead(self.rank, self._dead)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RankDead(self.rank,
                                   f'ack timeout after {timeout}s')
                self._cond.wait(timeout=remaining)

    def close(self) -> None:
        self._inbox.put(None)
        self._thread.join(timeout=5)


class TcpRank(RankChannel):
    """A follower over TCP (JSON lines, one ack line per command) —
    the real-slice transport; rank 0 accepts one connection per rank
    on the coordinator port from the gang env contract."""

    def __init__(self, rank: int, conn: socket.socket) -> None:
        self.rank = rank
        self._conn = conn
        self._rfile = conn.makefile('r', encoding='utf-8')
        self._wfile = conn.makefile('w', encoding='utf-8')
        self._acked = -1
        self._dead: Optional[str] = None
        self._cond = threading.Condition()
        self._reader = threading.Thread(target=self._read_acks,
                                        daemon=True,
                                        name=f'slice-rank-{rank}-acks')
        self._reader.start()

    def _read_acks(self) -> None:
        try:
            for line in self._rfile:
                ack = json.loads(line)
                if ack.get('status') != 'ok':
                    with self._cond:
                        self._dead = str(ack.get('error') or
                                         'command failed')
                        self._cond.notify_all()
                    return
                _M_RANK_TICKS.labels(rank=str(self.rank)).inc()
                with self._cond:
                    self._acked = int(ack['seq'])
                    self._cond.notify_all()
        except (OSError, ValueError) as e:
            with self._cond:
                self._dead = f'connection lost: {e}'
                self._cond.notify_all()
            return
        with self._cond:
            if self._dead is None:
                self._dead = 'connection closed'
            self._cond.notify_all()

    def send(self, cmd: Command) -> None:
        try:
            self._wfile.write(cmd.to_json() + '\n')
            self._wfile.flush()
        except (OSError, ValueError) as e:
            with self._cond:
                if self._dead is None:
                    self._dead = f'send failed: {e}'
                self._cond.notify_all()

    def wait_ack(self, seq: int, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._acked < seq:
                if self._dead is not None:
                    raise RankDead(self.rank, self._dead)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RankDead(self.rank,
                                   f'ack timeout after {timeout}s')
                self._cond.wait(timeout=remaining)

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass


def follower_serve(sock: socket.socket, rank: int,
                   executor: Optional[Callable[[Command], None]] = None,
                   ) -> None:
    """Follower loop for a REAL rank process: read commands off the
    coordinator connection, execute (the chaos boundary), ack each seq.
    Returns on `shutdown` or when the coordinator goes away; raises
    nothing — a failed command is acked with its error (rank 0 turns
    that into RankDead), then the loop exits because this rank is no
    longer in lockstep."""
    rfile = sock.makefile('r', encoding='utf-8')
    wfile = sock.makefile('w', encoding='utf-8')
    try:
        for line in rfile:
            cmd = Command.from_json(line)
            try:
                _execute(rank, cmd, executor)
            except Exception as e:  # pylint: disable=broad-except
                wfile.write(json.dumps({
                    'seq': cmd.seq, 'status': 'error',
                    'error': f'{type(e).__name__}: {e}'}) + '\n')
                wfile.flush()
                return
            wfile.write(json.dumps({'seq': cmd.seq,
                                    'status': 'ok'}) + '\n')
            wfile.flush()
            if cmd.kind == CMD_SHUTDOWN:
                return
    except (OSError, ValueError):
        return


def accept_followers(port: int, num_followers: int,
                     timeout: float = 120.0) -> List[TcpRank]:
    """Rank 0 side of the TCP transport: accept one connection per
    follower rank (each identifies itself with a hello line)."""
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(('0.0.0.0', port))
    server.listen(num_followers)
    server.settimeout(timeout)
    channels: List[TcpRank] = []
    try:
        while len(channels) < num_followers:
            conn, _ = server.accept()
            hello = conn.makefile('r', encoding='utf-8').readline()
            rank = int(json.loads(hello)['rank'])
            channels.append(TcpRank(rank, conn))
    finally:
        server.close()
    return channels


def follower_connect(address: str, rank: int,
                     timeout: float = 120.0) -> socket.socket:
    """Follower side: connect to rank 0's coordinator port and say
    hello (host:port, e.g. from SKYTPU_COORDINATOR_ADDRESS + offset)."""
    host, _, port = address.rpartition(':')
    deadline = time.monotonic() + timeout
    while True:
        try:
            sock = socket.create_connection((host or '127.0.0.1',
                                             int(port)), timeout=10)
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    sock.sendall((json.dumps({'rank': rank}) + '\n').encode())
    return sock


class SliceCoordinator:
    """Rank 0's view of the gang: broadcast commands, collect acks,
    track rank health.  `num_hosts` includes rank 0 itself (which
    executes inline — its dispatch is the real one in emulated mode)."""

    def __init__(self, num_hosts: int,
                 channels: Optional[List[RankChannel]] = None,
                 ack_timeout: float = _ACK_TIMEOUT_S) -> None:
        if num_hosts < 1:
            raise ValueError(f'num_hosts must be >= 1, got {num_hosts}')
        self.num_hosts = int(num_hosts)
        self._ack_timeout = float(ack_timeout)
        if channels is None:
            channels = [LocalRank(rank)
                        for rank in range(1, self.num_hosts)]
        if len(channels) != self.num_hosts - 1:
            raise ValueError(
                f'{self.num_hosts} hosts need {self.num_hosts - 1} '
                f'follower channels, got {len(channels)}')
        self._channels = channels
        self._seq = 0
        self._dead: Dict[int, str] = {}
        self._lock = threading.Lock()
        self._sync_total_s = 0.0
        self._sync_count = 0
        self._closed = False
        _M_RANKS_ALIVE.set(self.num_hosts)

    # ------------------------------------------------------------ health

    @property
    def dead_ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)

    @property
    def degraded(self) -> bool:
        with self._lock:
            return bool(self._dead)

    def ranks_alive(self) -> int:
        with self._lock:
            return self.num_hosts - len(self._dead)

    def sync_ms_mean(self) -> float:
        with self._lock:
            if not self._sync_count:
                return 0.0
            return self._sync_total_s / self._sync_count * 1e3

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            dead = sorted(self._dead)
            syncs = self._sync_count
            mean_ms = (self._sync_total_s / syncs * 1e3) if syncs else 0.0
        return {
            'num_hosts': self.num_hosts,
            'ranks_alive': self.num_hosts - len(dead),
            'dead_ranks': dead,
            'degraded': bool(dead),
            'sync_count': syncs,
            'sync_ms_mean': round(mean_ms, 4),
        }

    # --------------------------------------------------------- broadcast

    def broadcast(self, kind: str, **payload: Any) -> float:
        """Send one command to every follower and wait for all acks;
        rank 0 executes inline.  Returns the sync wall time (seconds).
        Raises RankDead on the FIRST command after any rank died — the
        caller (the engine tick wrapper) fails the replica as a unit."""
        with self._lock:
            if self._dead:
                rank = sorted(self._dead)[0]
                raise RankDead(rank, self._dead[rank])
            self._seq += 1
            cmd = Command(kind=kind, seq=self._seq, payload=payload)
        t0 = time.perf_counter()
        # Rank 0 executes inline (its chaos site fires like any other
        # rank's — `where: {rank: 0}` kills the head).
        try:
            _execute(0, cmd, None)
        except Exception as e:  # pylint: disable=broad-except
            self._mark_dead(0, f'{type(e).__name__}: {e}')
            raise RankDead(0, f'{type(e).__name__}: {e}') from e
        for channel in self._channels:
            channel.send(cmd)
        for channel in self._channels:
            try:
                channel.wait_ack(cmd.seq, self._ack_timeout)
            except RankDead as e:
                self._mark_dead(e.rank, e.reason)
                raise
        dt = time.perf_counter() - t0
        with self._lock:
            self._sync_total_s += dt
            self._sync_count += 1
        _M_SYNC_SECONDS.observe(dt)
        return dt

    def _mark_dead(self, rank: int, reason: str) -> None:
        with self._lock:
            if rank in self._dead:
                return
            self._dead[rank] = reason
            alive = self.num_hosts - len(self._dead)
        _M_RANK_DEATHS.labels(rank=str(rank)).inc()
        _M_RANKS_ALIVE.set(alive)
        logger.warning(f'slice rank {rank} died ({reason}); replica '
                       f'degraded to {alive}/{self.num_hosts} ranks')

    def tick(self) -> float:
        return self.broadcast(CMD_TICK)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Best-effort shutdown command so real followers exit their
        # loops; dead ranks are already gone.
        try:
            with self._lock:
                self._seq += 1
                cmd = Command(kind=CMD_SHUTDOWN, seq=self._seq)
            for channel in self._channels:
                channel.send(cmd)
        except Exception:  # pylint: disable=broad-except
            pass
        for channel in self._channels:
            channel.close()
