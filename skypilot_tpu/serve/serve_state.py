"""Serve state store: services + replicas (sqlite).

Parity: /root/reference/sky/serve/serve_state.py (ServiceStatus,
ReplicaStatus tables on the controller).
"""
from __future__ import annotations

import enum
import json
import os
import pathlib
import sqlite3
import time
from typing import Any, Dict, List, Optional


class ServiceStatus(enum.Enum):
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'
    READY = 'READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    FAILED_CLEANUP = 'FAILED_CLEANUP'
    NO_REPLICA = 'NO_REPLICA'

    def is_terminal(self) -> bool:
        return self in (ServiceStatus.FAILED,
                        ServiceStatus.FAILED_CLEANUP)


class ReplicaStatus(enum.Enum):
    PENDING = 'PENDING'
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'
    READY = 'READY'
    NOT_READY = 'NOT_READY'
    # Graceful retirement in progress: the LB stopped routing here, the
    # replica's HTTP fronts 503 new generates, and the engine finishes
    # its in-flight decodes before the cluster is torn down (bounded by
    # SKYTPU_SERVE_DRAIN_TIMEOUT_S).  Non-terminal: the drain monitor
    # in replica_managers owns the transition to TERMINATED.
    DRAINING = 'DRAINING'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    FAILED_INITIAL_DELAY = 'FAILED_INITIAL_DELAY'
    FAILED_PROBING = 'FAILED_PROBING'
    FAILED_PROVISION = 'FAILED_PROVISION'
    PREEMPTED = 'PREEMPTED'
    # Cluster torn down; row kept for history and id monotonicity
    # (parity: the reference keeps terminal replica records).
    TERMINATED = 'TERMINATED'

    def is_terminal(self) -> bool:
        return self in (ReplicaStatus.FAILED,
                        ReplicaStatus.FAILED_INITIAL_DELAY,
                        ReplicaStatus.FAILED_PROBING,
                        ReplicaStatus.FAILED_PROVISION,
                        ReplicaStatus.PREEMPTED,
                        ReplicaStatus.TERMINATED)

    @classmethod
    def failed_statuses(cls) -> List['ReplicaStatus']:
        return [s for s in cls if s.is_terminal()]


_CREATE_SERVICES = """\
CREATE TABLE IF NOT EXISTS services (
    name TEXT PRIMARY KEY,
    status TEXT,
    controller_port INTEGER,
    load_balancer_port INTEGER,
    controller_pid INTEGER,
    lb_pid INTEGER,
    spec_json TEXT,
    task_yaml_path TEXT,
    version INTEGER DEFAULT 1,
    created_at REAL,
    router_ports TEXT
)"""

_CREATE_REPLICAS = """\
CREATE TABLE IF NOT EXISTS replicas (
    service_name TEXT,
    replica_id INTEGER,
    cluster_name TEXT,
    status TEXT,
    url TEXT,
    is_spot INTEGER DEFAULT 0,
    version INTEGER DEFAULT 1,
    launched_at REAL,
    role TEXT DEFAULT 'mixed',
    num_hosts INTEGER DEFAULT 1,
    drain_started_at REAL,
    region TEXT,
    PRIMARY KEY (service_name, replica_id)
)"""


def _migrate(conn: sqlite3.Connection) -> None:
    """Additive migrations for DBs created before a column existed
    (same PRAGMA pattern as jobs/state.py)."""
    columns = {row[1] for row in
               conn.execute('PRAGMA table_info(replicas)')}
    if 'role' not in columns:
        conn.execute("ALTER TABLE replicas ADD COLUMN role TEXT "
                     "DEFAULT 'mixed'")
    if 'num_hosts' not in columns:
        # Multi-host slice replicas (ISSUE 9): how many gang-scheduled
        # hosts this replica spans; 1 for every pre-slice row.
        conn.execute('ALTER TABLE replicas ADD COLUMN num_hosts '
                     'INTEGER DEFAULT 1')
    if 'drain_started_at' not in columns:
        # Graceful drain (ISSUE 10): persisted so the drain timeout
        # survives controller restarts (an interrupted drain resumes
        # with its original clock, never a fresh one).
        conn.execute('ALTER TABLE replicas ADD COLUMN '
                     'drain_started_at REAL')
    if 'region' not in columns:
        # Multi-region placement (ISSUE 15): which region the
        # optimizer placed this replica in; NULL for single-region
        # services.
        conn.execute('ALTER TABLE replicas ADD COLUMN region TEXT')
    service_columns = {row[1] for row in
                       conn.execute('PRAGMA table_info(services)')}
    if 'router_ports' not in service_columns:
        # Router tier (ISSUE 15): JSON list of every router instance
        # port; load_balancer_port stays the first entry for
        # single-router compat.
        conn.execute('ALTER TABLE services ADD COLUMN '
                     'router_ports TEXT')


def _db_path() -> str:
    path = os.environ.get('SKYTPU_SERVE_DB')
    if path is None:
        from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
        path = os.path.join(common_utils.skytpu_home(), 'serve.db')
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    return path


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(), timeout=10)
    conn.execute(_CREATE_SERVICES)
    conn.execute(_CREATE_REPLICAS)
    _migrate(conn)
    return conn


# ---------------------------------------------------------------- services


def add_service(name: str, spec_json: Dict[str, Any],
                task_yaml_path: str) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO services (name, status, spec_json, '
            'task_yaml_path, created_at) VALUES (?,?,?,?,?)',
            (name, ServiceStatus.CONTROLLER_INIT.value,
             json.dumps(spec_json), task_yaml_path, time.time()))


def set_service_status(name: str, status: ServiceStatus) -> None:
    with _conn() as conn:
        conn.execute('UPDATE services SET status=? WHERE name=?',
                     (status.value, name))


def set_service_ports(name: str, controller_port: int,
                      lb_port: int,
                      router_ports: Optional[List[int]] = None) -> None:
    """lb_port is the tier's first router (single-router compat);
    router_ports records every instance when a tier is running."""
    with _conn() as conn:
        conn.execute(
            'UPDATE services SET controller_port=?, load_balancer_port=?, '
            'router_ports=? WHERE name=?',
            (controller_port, lb_port,
             json.dumps(router_ports) if router_ports else None, name))


def set_router_ports(name: str, router_ports: List[int]) -> None:
    """Record the live router-tier ports (and keep load_balancer_port
    pointed at the first surviving instance)."""
    with _conn() as conn:
        conn.execute(
            'UPDATE services SET router_ports=?, load_balancer_port=? '
            'WHERE name=?',
            (json.dumps(router_ports),
             router_ports[0] if router_ports else None, name))


def get_router_ports(record: Dict[str, Any]) -> List[int]:
    """Every router port of a service record (falls back to the single
    load_balancer_port for pre-tier rows)."""
    raw = record.get('router_ports')
    if raw:
        try:
            ports = json.loads(raw)
            if isinstance(ports, list) and ports:
                return [int(p) for p in ports]
        except (json.JSONDecodeError, TypeError, ValueError):
            pass
    lb_port = record.get('load_balancer_port')
    return [int(lb_port)] if lb_port else []


def set_service_pids(name: str, controller_pid: Optional[int] = None,
                     lb_pid: Optional[int] = None) -> None:
    with _conn() as conn:
        if controller_pid is not None:
            conn.execute('UPDATE services SET controller_pid=? '
                         'WHERE name=?', (controller_pid, name))
        if lb_pid is not None:
            conn.execute('UPDATE services SET lb_pid=? WHERE name=?',
                         (lb_pid, name))


def get_service(name: str) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        row = conn.execute('SELECT * FROM services WHERE name=?',
                           (name,)).fetchone()
    if row is None:
        return None
    rec = dict(row)
    rec['spec'] = json.loads(rec.pop('spec_json') or '{}')
    return rec


def get_services() -> List[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        rows = conn.execute(
            'SELECT * FROM services ORDER BY created_at').fetchall()
    out = []
    for row in rows:
        rec = dict(row)
        rec['spec'] = json.loads(rec.pop('spec_json') or '{}')
        out.append(rec)
    return out


def remove_service(name: str) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM services WHERE name=?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name=?', (name,))


def update_service_spec(name: str, spec_json: Dict[str, Any],
                        task_yaml_path: str) -> int:
    """Install a new spec/task version; returns the new version."""
    with _conn() as conn:
        conn.execute(
            'UPDATE services SET spec_json=?, task_yaml_path=?, '
            'version=version+1 WHERE name=?',
            (json.dumps(spec_json), task_yaml_path, name))
        row = conn.execute('SELECT version FROM services WHERE name=?',
                           (name,)).fetchone()
    return row[0] if row else 1


# ---------------------------------------------------------------- replicas


def add_replica(service_name: str, replica_id: int, cluster_name: str,
                is_spot: bool = False, version: int = 1,
                role: str = 'mixed', num_hosts: int = 1,
                region: Optional[str] = None) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO replicas (service_name, replica_id, '
            'cluster_name, status, is_spot, version, launched_at, role, '
            'num_hosts, region) VALUES (?,?,?,?,?,?,?,?,?,?)',
            (service_name, replica_id, cluster_name,
             ReplicaStatus.PROVISIONING.value, int(is_spot), version,
             time.time(), role, int(num_hosts), region))


def set_replica_status(service_name: str, replica_id: int,
                       status: ReplicaStatus,
                       url: Optional[str] = None) -> None:
    with _conn() as conn:
        if url is not None:
            conn.execute(
                'UPDATE replicas SET status=?, url=? '
                'WHERE service_name=? AND replica_id=?',
                (status.value, url, service_name, replica_id))
        else:
            conn.execute(
                'UPDATE replicas SET status=? '
                'WHERE service_name=? AND replica_id=?',
                (status.value, service_name, replica_id))


def set_replica_draining(service_name: str, replica_id: int,
                         drain_started_at: float) -> None:
    """Enter DRAINING with a persisted drain clock (the timeout must
    survive controller restarts; resumed drains keep the original
    start, never reset it)."""
    with _conn() as conn:
        conn.execute(
            'UPDATE replicas SET status=?, drain_started_at=? '
            'WHERE service_name=? AND replica_id=?',
            (ReplicaStatus.DRAINING.value, drain_started_at,
             service_name, replica_id))


def set_replica_role(service_name: str, replica_id: int,
                     role: str) -> None:
    """Persist a live role morph: the DB role column tracks the role
    the replica currently serves (launch role until the first morph),
    so status tables and scrape targets never show a stale pool."""
    with _conn() as conn:
        conn.execute(
            'UPDATE replicas SET role=? '
            'WHERE service_name=? AND replica_id=?',
            (role, service_name, replica_id))


def remove_replica(service_name: str, replica_id: int) -> None:
    with _conn() as conn:
        conn.execute(
            'DELETE FROM replicas WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        rows = conn.execute(
            'SELECT * FROM replicas WHERE service_name=? '
            'ORDER BY replica_id', (service_name,)).fetchall()
    return [dict(r) for r in rows]


def allocate_replica(service_name: str, cluster_prefix: str,
                     is_spot: bool = False, version: int = 1,
                     role: str = 'mixed', num_hosts: int = 1,
                     region: Optional[str] = None) -> int:
    """Atomically claim the next replica id and insert its row (ids stay
    monotonic and unique under concurrent scale-ups)."""
    with _conn() as conn:
        conn.execute(
            'INSERT INTO replicas (service_name, replica_id, '
            'cluster_name, status, is_spot, version, launched_at, role, '
            'num_hosts, region) '
            "SELECT ?, COALESCE(MAX(replica_id), 0) + 1, '', ?, ?, ?, "
            '?, ?, ?, ? FROM replicas WHERE service_name=?',
            (service_name, ReplicaStatus.PROVISIONING.value,
             int(is_spot), version, time.time(), role, int(num_hosts),
             region, service_name))
        rid = conn.execute(
            'SELECT MAX(replica_id) FROM replicas WHERE service_name=?',
            (service_name,)).fetchone()[0]
        conn.execute(
            'UPDATE replicas SET cluster_name=? '
            'WHERE service_name=? AND replica_id=?',
            (f'{cluster_prefix}-{rid}', service_name, rid))
    return rid
