"""KV handoff wire format: page-granular cache transfer between replicas.

Prefill/decode disaggregation moves the KV a prefill replica computed
onto the decode replica that will stream the tokens.  The transfer
unit is PR 7's page: the payload carries the prompt's FULL prefilled
pages in page-major layout `[L, n_pages, h_kv, page_size, d]` plus the
chain hashes that name them, and the decode replica adopts them
through its own prefix cache — a handoff is literally a remote prefix-
cache fill, so the same request repeated later hits the same pages.

Wire format (JSON over the replicas' existing HTTP):

    {"version": 1, "page_size": P, "n_pages": N,
     "hashes": [h0, h1, ...],            # chain hashes, page order
     "dtype": "float32" | "int8",
     "shape": [L, N, h_kv, P, d],
     "k": "<b64>", "v": "<b64>",          # raw little-endian bytes
     "k_scale": "<b64>", "v_scale": ...}  # int8 only: f32 [L,N,h_kv,P]

Floating payloads are always float32 on the wire (bf16 -> f32 is
exact, so bf16 pools round-trip losslessly); int8 payloads carry the
per-page-per-head-per-token scales exactly as `models/decode._quant_kv`
produced them, and requantization on the receiving pool is byte-stable
— decode-after-handoff is token-exact against single-replica serving
(pinned by tests/unit/test_kv_handoff.py).

The tail of the prompt — positions past the last FULL page — is NOT
shipped: the decode replica chunk-prefills it locally (< one page of
tokens), exactly like a partial prefix-cache hit.  That keeps the
transfer page-granular and reuses the PR 7 admission path unchanged.

Binary wire (``application/octet-stream``): the JSON/base64 wire above
costs 4/3x the page bytes in base64 alone, plus a json.dumps/loads of
megabyte strings on both sides.  The binary frame ships the SAME
fields with the arrays raw:

    b'SKTH1\\n' | u32 header_len | header JSON | k | v [| k_scale | v_scale]

where the header is the JSON payload minus the blobs (version,
page_size, n_pages, hashes, dtype, shape) and the arrays follow
little-endian, C-contiguous, in that fixed order.  `encode_binary` /
`decode_binary` are the codec; the replica fronts accept it on
`/prefill_export` (request `{"wire": "binary"}` -> octet-stream
response) and `/kv_import` (octet-stream request body), and the LB
prefers it (SKYTPU_LB_HANDOFF_BINARY), falling back to JSON/base64
when either leg refuses — old replicas keep working mid-rollout.
"""
from __future__ import annotations

import base64
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

WIRE_VERSION = 1

# Binary-frame magic (versioned: bump with WIRE_VERSION).
BINARY_MAGIC = b'SKTH1\n'
CONTENT_TYPE_BINARY = 'application/octet-stream'


class HandoffError(RuntimeError):
    """The handoff cannot proceed (wrong mode, mismatched geometry,
    malformed payload).  Routers treat it as 'fall back to local
    prefill' — never a failed request."""


class HandoffRejected(HandoffError):
    """The decode replica refused the import right now (chaos deny /
    shedding); the request must still complete via local prefill."""


def _b64(arr: np.ndarray) -> str:
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode()


def _unb64(data: str, dtype: str, shape: Sequence[int]) -> np.ndarray:
    raw = base64.b64decode(data)
    arr = np.frombuffer(raw, dtype=np.dtype(dtype))
    expect = int(np.prod(shape))
    if arr.size != expect:
        raise HandoffError(
            f'payload size mismatch: {arr.size} elements for shape '
            f'{list(shape)} ({expect})')
    return arr.reshape(shape)


def encode_payload(hashes: Sequence[int], page_size: int,
                   k_pages: np.ndarray, v_pages: np.ndarray,
                   k_scale: Optional[np.ndarray] = None,
                   v_scale: Optional[np.ndarray] = None
                   ) -> Dict[str, Any]:
    """Pack exported pages for the wire.  k/v are `[L, N, h_kv, ps, d]`
    — float32, or int8 with f32 scales `[L, N, h_kv, ps]`."""
    quantized = k_scale is not None
    payload: Dict[str, Any] = {
        'version': WIRE_VERSION,
        'page_size': int(page_size),
        'n_pages': int(k_pages.shape[1]),
        'hashes': [int(h) for h in hashes],
        'dtype': 'int8' if quantized else 'float32',
        'shape': [int(s) for s in k_pages.shape],
        'k': _b64(k_pages),
        'v': _b64(v_pages),
    }
    if quantized:
        payload['k_scale'] = _b64(np.asarray(k_scale, np.float32))
        payload['v_scale'] = _b64(np.asarray(v_scale, np.float32))
    return payload


def encode_binary(hashes: Sequence[int], page_size: int,
                  k_pages: np.ndarray, v_pages: np.ndarray,
                  k_scale: Optional[np.ndarray] = None,
                  v_scale: Optional[np.ndarray] = None) -> bytes:
    """Pack exported pages as the binary frame (see module docs):
    header JSON + raw little-endian arrays in fixed order.  ~25% fewer
    bytes on the wire than the base64 form of the same payload, and no
    megabyte-string json round trip on either side."""
    import json  # pylint: disable=import-outside-toplevel
    quantized = k_scale is not None
    header = {
        'version': WIRE_VERSION,
        'page_size': int(page_size),
        'n_pages': int(k_pages.shape[1]),
        'hashes': [int(h) for h in hashes],
        'dtype': 'int8' if quantized else 'float32',
        'shape': [int(s) for s in k_pages.shape],
    }
    head = json.dumps(header).encode()
    parts = [BINARY_MAGIC, len(head).to_bytes(4, 'little'), head,
             np.ascontiguousarray(k_pages).tobytes(),
             np.ascontiguousarray(v_pages).tobytes()]
    if quantized:
        parts.append(np.ascontiguousarray(
            np.asarray(k_scale, np.float32)).tobytes())
        parts.append(np.ascontiguousarray(
            np.asarray(v_scale, np.float32)).tobytes())
    return b''.join(parts)


def decode_binary(data: bytes) -> Dict[str, Any]:
    """Unpack a binary frame into the same dict `decode_payload`
    returns: {'hashes', 'page_size', 'k', 'v'[, 'k_scale', 'v_scale']}
    with k/v `[L, N, h_kv, ps, d]`."""
    import json  # pylint: disable=import-outside-toplevel
    if not data.startswith(BINARY_MAGIC):
        raise HandoffError('not a binary handoff frame (bad magic)')
    off = len(BINARY_MAGIC)
    if len(data) < off + 4:
        raise HandoffError('truncated binary handoff frame')
    head_len = int.from_bytes(data[off:off + 4], 'little')
    off += 4
    if len(data) < off + head_len:
        raise HandoffError('truncated binary handoff header')
    try:
        header = json.loads(data[off:off + head_len])
    except (ValueError, UnicodeDecodeError) as e:
        raise HandoffError(f'malformed binary handoff header: {e}') \
            from e
    off += head_len
    version = header.get('version')
    if version != WIRE_VERSION:
        raise HandoffError(f'unsupported handoff wire version '
                           f'{version!r} (have {WIRE_VERSION})')
    try:
        shape = [int(s) for s in header['shape']]
        hashes = [int(h) for h in header['hashes']]
        page_size = int(header['page_size'])
        dtype = header['dtype']
    except (KeyError, ValueError, TypeError) as e:
        raise HandoffError(f'malformed binary handoff header: {e}') \
            from e
    if len(shape) != 5 or shape[3] != page_size or \
            shape[1] != len(hashes):
        raise HandoffError(f'bad binary handoff geometry: shape '
                           f'{shape}, page_size {page_size}, '
                           f'{len(hashes)} hashes')
    if dtype not in ('float32', 'int8'):
        raise HandoffError(f'unsupported handoff dtype {dtype!r}')
    count = int(np.prod(shape))
    itemsize = 1 if dtype == 'int8' else 4

    def take(n_bytes: int, np_dtype, arr_shape) -> np.ndarray:
        nonlocal off
        if len(data) < off + n_bytes:
            raise HandoffError('truncated binary handoff arrays')
        arr = np.frombuffer(data, dtype=np_dtype, count=int(
            np.prod(arr_shape)), offset=off).reshape(arr_shape)
        off += n_bytes
        return arr

    k = take(count * itemsize, dtype, shape)
    v = take(count * itemsize, dtype, shape)
    out = {'hashes': hashes, 'page_size': page_size, 'k': k, 'v': v}
    if dtype == 'int8':
        scale_count = int(np.prod(shape[:4]))
        out['k_scale'] = take(scale_count * 4, np.float32, shape[:4])
        out['v_scale'] = take(scale_count * 4, np.float32, shape[:4])
    if off != len(data):
        raise HandoffError(
            f'binary handoff frame has {len(data) - off} trailing '
            f'bytes')
    return out


def decode_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Unpack a wire payload into page arrays ready for pool adoption:
    `{'hashes', 'page_size', 'k', 'v'}` with k/v
    `[L, N, h_kv, ps, d]`.  float32 payloads decode as float32; int8
    payloads stay int8 WITH their scales (`k_scale`/`v_scale`,
    `[L, N, h_kv, ps]` f32) — an int8 pool adopts them byte-for-byte
    without a dequantize/requantize round trip (the engine dequantizes
    only when the receiving pool is float)."""
    try:
        version = int(payload.get('version', 0))
    except (TypeError, ValueError):
        version = 0
    if version != WIRE_VERSION:
        raise HandoffError(
            f'unsupported handoff wire version '
            f'{payload.get("version")!r} (have {WIRE_VERSION})')
    try:
        shape = [int(s) for s in payload['shape']]
        hashes: List[int] = [int(h) for h in payload['hashes']]
        page_size = int(payload['page_size'])
        dtype = payload['dtype']
        if len(shape) != 5:
            raise HandoffError(f'bad page shape {shape}')
        if shape[3] != page_size:
            raise HandoffError(
                f'shape page dim {shape[3]} != page_size {page_size}')
        if shape[1] != len(hashes):
            raise HandoffError(
                f'{shape[1]} pages but {len(hashes)} chain hashes')
        scales = {}
        if dtype == 'int8':
            k = _unb64(payload['k'], 'int8', shape)
            v = _unb64(payload['v'], 'int8', shape)
            scales = {
                'k_scale': _unb64(payload['k_scale'], 'float32',
                                  shape[:4]),
                'v_scale': _unb64(payload['v_scale'], 'float32',
                                  shape[:4]),
            }
        elif dtype == 'float32':
            k = _unb64(payload['k'], 'float32', shape)
            v = _unb64(payload['v'], 'float32', shape)
        else:
            raise HandoffError(f'unsupported handoff dtype {dtype!r}')
    except HandoffError:
        raise
    except (KeyError, ValueError, TypeError) as e:
        raise HandoffError(f'malformed handoff payload: {e}') from e
    return {'hashes': hashes, 'page_size': page_size, 'k': k, 'v': v,
            **scales}
