"""Load balancer: asyncio streaming HTTP reverse proxy over ready replicas.

Parity: /root/reference/sky/serve/load_balancer.py:22-205
(SkyServeLoadBalancer: syncs ready-replica URLs + reports request
timestamps to the controller every sync interval :58-111; per-request
replica pick + stream-proxy via FastAPI/httpx) and
load_balancing_policies.py.  Here the proxy is a single-threaded
asyncio server (no per-connection threads): request bodies stream to
the replica as they arrive and response bytes stream back chunk-by-
chunk with backpressure — SSE / LLM token streams are never buffered.
Policies: round_robin and least_connections (better for LLM serving,
where generation lengths make request costs wildly uneven).
"""
from __future__ import annotations

import asyncio
import os
import ssl as ssl_lib
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

import requests

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing

logger = sky_logging.init_logger(__name__)

# Registry instruments (GET /metrics on the replica fronts; the LB has
# no HTTP exposition of its own yet — scrape via
# metrics.start_exposition_server when running it standalone).
_M_REQUESTS = metrics_lib.counter(
    'skytpu_lb_requests_total',
    'Requests proxied, by load-balancing policy.', ('policy',))
_M_UPSTREAM_INFLIGHT = metrics_lib.gauge(
    'skytpu_lb_upstream_inflight',
    'In-flight proxied requests per upstream replica.', ('upstream',))
_M_PROXY_LATENCY = metrics_lib.histogram(
    'skytpu_lb_proxy_seconds',
    'Client head parsed until upstream EOF relayed (includes full '
    'token streams).')
_M_NO_REPLICA = metrics_lib.counter(
    'skytpu_lb_no_replica_total',
    'Requests answered 503: no ready replicas.')
_M_UPSTREAM_ERRORS = metrics_lib.counter(
    'skytpu_lb_upstream_errors_total',
    'Requests answered 502: replica unreachable or dropped the '
    'request before any response byte.')
_M_DROPPED_TIMESTAMPS = metrics_lib.counter(
    'skytpu_lb_dropped_request_timestamps_total',
    'QPS samples dropped (oldest-first) because controller sync '
    'kept failing.')
_M_SYNC_FAILURES = metrics_lib.counter(
    'skytpu_lb_controller_sync_failures_total',
    'Controller sync attempts that failed.')

_REQUEST_ID_KEY = tracing.REQUEST_ID_HEADER.lower()


def _max_pending_timestamps() -> int:
    """Cap on buffered QPS samples while controller sync is failing
    (drop-oldest beyond it — the autoscaler signal degrades, the LB
    process does not)."""
    return int(os.environ.get('SKYTPU_LB_MAX_PENDING_TIMESTAMPS',
                              '100000'))

# Hop-by-hop headers never forwarded (RFC 9110 §7.6.1).  Content-Length
# and Transfer-Encoding ARE forwarded: the body bytes pass through with
# their original framing.
_HOP_HEADERS = {'connection', 'keep-alive', 'proxy-authenticate',
                'proxy-authorization', 'te', 'trailers', 'upgrade'}
_MAX_HEAD = 64 * 1024
_UPSTREAM_CONNECT_TIMEOUT = 10.0
# Max silence between upstream response chunks.  Generous because a
# busy engine can legitimately take minutes before the first token, but
# finite so a wedged replica releases the client connection (and the
# least_connections in-flight count) instead of pinning both forever.
_UPSTREAM_IDLE_TIMEOUT = float(
    os.environ.get('SKYTPU_LB_UPSTREAM_IDLE_TIMEOUT', '300'))
_CHUNK = 64 * 1024


def _lb_sync_interval() -> float:
    return float(os.environ.get('SKYTPU_LB_SYNC_INTERVAL', '20'))


class LoadBalancingPolicy:

    def select(self, urls: List[str]) -> Optional[str]:
        raise NotImplementedError

    def acquire(self, url: str) -> None:  # request started
        del url

    def release(self, url: str) -> None:  # request finished (any outcome)
        del url


class RoundRobinPolicy(LoadBalancingPolicy):

    NAME = 'round_robin'

    def __init__(self) -> None:
        self._index = 0
        self._lock = threading.Lock()

    def select(self, urls: List[str]) -> Optional[str]:
        if not urls:
            return None
        with self._lock:
            url = urls[self._index % len(urls)]
            self._index += 1
        return url


class LeastConnectionsPolicy(LoadBalancingPolicy):
    """Pick the replica with the fewest in-flight requests."""

    NAME = 'least_connections'

    def __init__(self) -> None:
        self._inflight: Dict[str, int] = {}
        self._lock = threading.Lock()

    def select(self, urls: List[str]) -> Optional[str]:
        if not urls:
            return None
        with self._lock:
            return min(urls, key=lambda u: (self._inflight.get(u, 0), u))

    def acquire(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1

    def release(self, url: str) -> None:
        with self._lock:
            n = self._inflight.get(url, 0) - 1
            if n <= 0:
                self._inflight.pop(url, None)
            else:
                self._inflight[url] = n


POLICIES = {
    RoundRobinPolicy.NAME: RoundRobinPolicy,
    LeastConnectionsPolicy.NAME: LeastConnectionsPolicy,
}


def make_policy(name: Optional[str]) -> LoadBalancingPolicy:
    if name is None:
        return RoundRobinPolicy()
    if name not in POLICIES:
        raise ValueError(f'Unknown load_balancing_policy {name!r}; '
                         f'have {sorted(POLICIES)}')
    return POLICIES[name]()


class _HeadTooLarge(Exception):
    pass


async def _read_head(reader: asyncio.StreamReader) -> bytes:
    # The server's StreamReader limit is 2 * _MAX_HEAD, so readuntil
    # raising LimitOverrunError IS the too-large signal.
    try:
        return await reader.readuntil(b'\r\n\r\n')
    except asyncio.LimitOverrunError as e:
        raise _HeadTooLarge() from e


def _parse_head(head: bytes) -> Tuple[str, List[Tuple[str, str]]]:
    """Returns (start_line, [(name, value), ...])."""
    lines = head.decode('latin-1').split('\r\n')
    headers = []
    for line in lines[1:]:
        if not line:
            continue
        name, _, value = line.partition(':')
        headers.append((name.strip(), value.strip()))
    return lines[0], headers


def _body_framing(headers: List[Tuple[str, str]]) -> Tuple[str, int]:
    """('length', N) | ('chunked', 0) | ('none', 0)."""
    for name, value in headers:
        lname = name.lower()
        if lname == 'transfer-encoding' and 'chunked' in value.lower():
            return 'chunked', 0
        if lname == 'content-length':
            try:
                return 'length', int(value)
            except ValueError:
                return 'none', 0
    return 'none', 0


async def _relay_body(reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter,
                      framing: Tuple[str, int]) -> None:
    """Stream a message body with its original framing preserved.

    Every read AND drain is idle-bounded: a replica that stops READING
    the request body wedges `drain()` (send buffers full) exactly like
    one that stops writing the response — both must release the client
    connection and the in-flight count, not pin them forever.
    """

    def _bounded(awaitable):
        return asyncio.wait_for(awaitable, timeout=_UPSTREAM_IDLE_TIMEOUT)

    kind, length = framing
    if kind == 'length':
        remaining = length
        while remaining > 0:
            chunk = await _bounded(reader.read(min(_CHUNK, remaining)))
            if not chunk:
                raise ConnectionError('body truncated')
            writer.write(chunk)
            await _bounded(writer.drain())
            remaining -= len(chunk)
    elif kind == 'chunked':
        # Pass chunks through verbatim while tracking the framing so we
        # know where the body ends (incl. the trailing CRLF / trailers).
        while True:
            size_line = await _bounded(reader.readline())
            writer.write(size_line)
            try:
                size = int(size_line.strip().split(b';')[0], 16)
            except ValueError as e:
                raise ConnectionError(f'bad chunk size {size_line!r}') from e
            if size == 0:
                # Trailers (if any) end with an empty line.
                while True:
                    trailer = await _bounded(reader.readline())
                    writer.write(trailer)
                    if trailer in (b'\r\n', b'\n', b''):
                        break
                await _bounded(writer.drain())
                return
            remaining = size + 2  # chunk data + CRLF
            while remaining > 0:
                chunk = await _bounded(
                    reader.read(min(_CHUNK, remaining)))
                if not chunk:
                    raise ConnectionError('chunk truncated')
                writer.write(chunk)
                remaining -= len(chunk)
            await _bounded(writer.drain())


async def _relay_until_eof(reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
    while True:
        chunk = await asyncio.wait_for(reader.read(_CHUNK),
                                       timeout=_UPSTREAM_IDLE_TIMEOUT)
        if not chunk:
            return
        writer.write(chunk)
        # Backpressure (never buffer a token stream) but idle-bounded:
        # a client that stops READING (zero receive window) must not
        # pin the replica connection + in-flight count any more than a
        # replica that stops writing.
        await asyncio.wait_for(writer.drain(),
                               timeout=_UPSTREAM_IDLE_TIMEOUT)


class _UpstreamError(Exception):
    """Failure before any response byte was relayed → client gets 502."""


def _simple_response(status: int, reason: str, body: bytes) -> bytes:
    return (f'HTTP/1.1 {status} {reason}\r\n'
            f'Content-Length: {len(body)}\r\n'
            f'Content-Type: text/plain\r\n'
            f'Connection: close\r\n\r\n').encode() + body


class SkyServeLoadBalancer:
    """Streams requests to replicas; reports QPS to the controller."""

    def __init__(self, controller_url: str, port: int = 0,
                 policy: Optional[LoadBalancingPolicy] = None) -> None:
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = policy or RoundRobinPolicy()
        self.ready_urls: List[str] = []
        self.request_timestamps: List[float] = []
        self.dropped_timestamps = 0
        self._sync_failures = 0       # consecutive; reset on success
        self._next_failure_warn = 1   # exponential-backoff WARNING
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()

    # ------------------------------------------------------ controller sync

    def _sync_with_controller(self) -> None:
        with self._lock:
            timestamps, self.request_timestamps = \
                self.request_timestamps, []
        try:
            resp = requests.post(
                self.controller_url + '/controller/load_balancer_sync',
                json={'request_timestamps': timestamps}, timeout=5)
            urls = resp.json().get('ready_replica_urls', [])
            with self._lock:
                self.ready_urls = urls
                if self._sync_failures:
                    logger.info(
                        f'LB sync recovered after '
                        f'{self._sync_failures} failed attempt(s)')
                self._sync_failures = 0
                self._next_failure_warn = 1
        except (requests.RequestException, ValueError) as e:
            # The samples go back on the (bounded) buffer so a
            # transient controller outage doesn't lose the QPS signal.
            with self._lock:
                self.request_timestamps = (timestamps +
                                           self.request_timestamps)
                self._trim_timestamps_locked()
                self._sync_failures += 1
                failures = self._sync_failures
                warn = failures >= self._next_failure_warn
                if warn:
                    self._next_failure_warn = max(
                        2, self._next_failure_warn * 2)
            _M_SYNC_FAILURES.inc()
            # WARNING with exponential backoff (attempt 1, 2, 4, 8,
            # ...), DEBUG otherwise: a controller that is down for an
            # hour must not emit 180 identical warnings.
            if warn:
                logger.warning(
                    f'LB sync failed ({failures} consecutive): {e}')
            else:
                logger.debug(f'LB sync failed ({failures}): {e}')

    def _trim_timestamps_locked(self) -> None:
        """Drop-oldest beyond the cap (call with self._lock held)."""
        cap = _max_pending_timestamps()
        overflow = len(self.request_timestamps) - cap
        if overflow > 0:
            del self.request_timestamps[:overflow]
            self.dropped_timestamps += overflow
            _M_DROPPED_TIMESTAMPS.inc(overflow)

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_with_controller()
            self._stop.wait(_lb_sync_interval())

    # -------------------------------------------------------------- proxy

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        target = None
        try:
            head = await asyncio.wait_for(_read_head(reader), timeout=60)
            start_line, headers = _parse_head(head)
            t_start = time.perf_counter()
            with self._lock:
                self.request_timestamps.append(time.time())
                self._trim_timestamps_locked()
                urls = list(self.ready_urls)
            target = self.policy.select(urls)
            _M_REQUESTS.labels(policy=getattr(
                self.policy, 'NAME', type(self.policy).__name__)).inc()
            if target is None:
                _M_NO_REPLICA.inc()
                writer.write(_simple_response(
                    503, 'Service Unavailable', b'No ready replicas.'))
                await writer.drain()
                return
            # acquire/release bracket EVERYTHING that can raise (bad
            # framing, disconnects mid-stream) or in-flight counts leak
            # and least_connections starves the replica forever.
            self.policy.acquire(target)
            inflight = _M_UPSTREAM_INFLIGHT.labels(upstream=target)
            inflight.inc()
            try:
                await self._proxy_to(target, reader, writer, start_line,
                                     headers)
                _M_PROXY_LATENCY.observe(time.perf_counter() - t_start)
            finally:
                inflight.dec()
                self.policy.release(target)
        except _HeadTooLarge:
            try:
                writer.write(_simple_response(
                    431, 'Request Header Fields Too Large',
                    b'Request head exceeds limit.'))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
        except _UpstreamError as e:
            # No response byte was relayed yet — a 502 is still clean.
            _M_UPSTREAM_ERRORS.inc()
            try:
                writer.write(_simple_response(
                    502, 'Bad Gateway', f'Bad gateway: {e}'.encode()))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError, ValueError, OSError):
            # Client went away or the stream broke mid-relay: close.
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _proxy_to(self, target: str, creader: asyncio.StreamReader,
                        cwriter: asyncio.StreamWriter, start_line: str,
                        headers: List[Tuple[str, str]]) -> None:
        split = urlsplit(target)
        host = split.hostname or '127.0.0.1'
        use_tls = split.scheme == 'https'
        port = split.port or (443 if use_tls else 80)
        try:
            ureader, uwriter = await asyncio.wait_for(
                asyncio.open_connection(
                    host, port,
                    ssl=ssl_lib.create_default_context() if use_tls
                    else None),
                timeout=_UPSTREAM_CONNECT_TIMEOUT)
        except (OSError, asyncio.TimeoutError) as e:
            raise _UpstreamError(f'cannot reach replica {target}: {e}') \
                from e
        try:
            # Expect: 100-continue — the client waits for our go-ahead
            # before sending the body (curl does this for large bodies);
            # answer it ourselves and strip the header upstream, since
            # we relay the body unconditionally.
            expects_continue = any(
                n.lower() == 'expect' and '100-continue' in v.lower()
                for n, v in headers)
            if expects_continue:
                cwriter.write(b'HTTP/1.1 100 Continue\r\n\r\n')
                await cwriter.drain()
            # Rewrite the head: drop hop-by-hop, pin Host, close after.
            out = [start_line]
            out.extend(f'{n}: {v}' for n, v in headers
                       if n.lower() not in _HOP_HEADERS and
                       n.lower() not in ('host', 'expect'))
            # The LB is the outermost layer: requests without an
            # X-SkyTPU-Request-Id get one here, so the replica's span
            # records and the client's response header line up
            # end to end.
            if not any(n.lower() == _REQUEST_ID_KEY
                       for n, _ in headers):
                out.append(f'{tracing.REQUEST_ID_HEADER}: '
                           f'{tracing.new_request_id()}')
            out.append(f'Host: {host}:{port}')
            out.append('Connection: close')
            try:
                uwriter.write(
                    ('\r\n'.join(out) + '\r\n\r\n').encode('latin-1'))
                await uwriter.drain()
                # Stream the request body with its original framing.
                await _relay_body(creader, uwriter, _body_framing(headers))
                # Idle timeout: a replica that accepts the connection
                # but never answers must not pin the client forever.
                first = await asyncio.wait_for(
                    ureader.read(_CHUNK), timeout=_UPSTREAM_IDLE_TIMEOUT)
            except (OSError, ConnectionError, asyncio.TimeoutError) as e:
                raise _UpstreamError(
                    f'replica {target} dropped the request: {e}') from e
            if not first:
                raise _UpstreamError(f'replica {target} sent no response')
            # Stream the response verbatim until upstream EOF: with
            # Connection: close the replica's EOF is the end marker, so
            # no response re-framing is needed and first bytes reach the
            # client as soon as the replica emits them.
            cwriter.write(first)
            await asyncio.wait_for(cwriter.drain(),
                                   timeout=_UPSTREAM_IDLE_TIMEOUT)
            await _relay_until_eof(ureader, cwriter)
        finally:
            try:
                uwriter.close()
                await uwriter.wait_closed()
            except (ConnectionError, OSError):
                pass

    # ---------------------------------------------------------------- run

    def _run_loop(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def serve():
            self._server = await asyncio.start_server(
                self._handle, '0.0.0.0', self.port, limit=2 * _MAX_HEAD)
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()
            async with self._server:
                await self._server.serve_forever()

        try:
            self._loop.run_until_complete(serve())
        except asyncio.CancelledError:
            pass
        finally:
            self._loop.close()

    def start(self) -> int:
        """Start proxy + sync threads; returns the bound LB port."""
        threading.Thread(target=self._run_loop, daemon=True).start()
        if not self._started.wait(10):
            raise RuntimeError('load balancer failed to bind')
        threading.Thread(target=self._sync_loop, daemon=True).start()
        logger.info(f'load balancer on :{self.port} -> '
                    f'{self.controller_url}')
        return self.port

    def stop(self) -> None:
        self._stop.set()
        loop, server = self._loop, self._server
        if loop is not None and server is not None:
            loop.call_soon_threadsafe(server.close)
