"""Load balancer: HTTP reverse proxy over ready replicas.

Parity: /root/reference/sky/serve/load_balancer.py:22-205
(SkyServeLoadBalancer: syncs ready-replica URLs + reports request
timestamps to the controller every sync interval :58-111; per-request
replica pick + stream-proxy) and load_balancing_policies.py
(RoundRobinPolicy).
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler
from http.server import ThreadingHTTPServer
from typing import List, Optional

import requests

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'proxy-authenticate',
                'proxy-authorization', 'te', 'trailers',
                'transfer-encoding', 'upgrade', 'host',
                'content-length'}


def _lb_sync_interval() -> float:
    return float(os.environ.get('SKYTPU_LB_SYNC_INTERVAL', '20'))


class LoadBalancingPolicy:

    def select(self, urls: List[str]) -> Optional[str]:
        raise NotImplementedError


class RoundRobinPolicy(LoadBalancingPolicy):

    NAME = 'round_robin'

    def __init__(self) -> None:
        self._index = 0
        self._lock = threading.Lock()

    def select(self, urls: List[str]) -> Optional[str]:
        if not urls:
            return None
        with self._lock:
            url = urls[self._index % len(urls)]
            self._index += 1
        return url


class LeastConnectionsPolicy(LoadBalancingPolicy):
    """Pick the replica with the fewest in-flight requests — better
    than round-robin for LLM serving, where generation lengths (and so
    request costs) are wildly uneven.  Callers must bracket the request
    with acquire/release."""

    NAME = 'least_connections'

    def __init__(self) -> None:
        self._inflight: dict = {}
        self._lock = threading.Lock()

    def select(self, urls: List[str]) -> Optional[str]:
        if not urls:
            return None
        with self._lock:
            return min(urls, key=lambda u: (self._inflight.get(u, 0), u))

    def acquire(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1

    def release(self, url: str) -> None:
        with self._lock:
            n = self._inflight.get(url, 0) - 1
            if n <= 0:
                self._inflight.pop(url, None)
            else:
                self._inflight[url] = n


POLICIES = {
    RoundRobinPolicy.NAME: RoundRobinPolicy,
    LeastConnectionsPolicy.NAME: LeastConnectionsPolicy,
}


def make_policy(name: Optional[str]) -> LoadBalancingPolicy:
    if name is None:
        return RoundRobinPolicy()
    if name not in POLICIES:
        raise ValueError(f'Unknown load_balancing_policy {name!r}; '
                         f'have {sorted(POLICIES)}')
    return POLICIES[name]()


class SkyServeLoadBalancer:

    def __init__(self, controller_url: str, port: int = 0,
                 policy: Optional[LoadBalancingPolicy] = None) -> None:
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = policy or RoundRobinPolicy()
        self.ready_urls: List[str] = []
        self.request_timestamps: List[float] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------ controller sync

    def _sync_with_controller(self) -> None:
        with self._lock:
            timestamps, self.request_timestamps = \
                self.request_timestamps, []
        try:
            resp = requests.post(
                self.controller_url + '/controller/load_balancer_sync',
                json={'request_timestamps': timestamps}, timeout=5)
            urls = resp.json().get('ready_replica_urls', [])
            with self._lock:
                self.ready_urls = urls
        except (requests.RequestException, ValueError) as e:
            logger.warning(f'LB sync failed: {e}')

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_with_controller()
            self._stop.wait(_lb_sync_interval())

    # -------------------------------------------------------------- proxy

    def _make_handler(self):
        lb = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args):
                del args

            def _proxy(self):
                with lb._lock:  # pylint: disable=protected-access
                    lb.request_timestamps.append(time.time())
                    urls = list(lb.ready_urls)
                target = lb.policy.select(urls)
                if target is None:
                    body = b'No ready replicas.'
                    self.send_response(503)
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # acquire/release must bracket EVERYTHING that can
                # raise (bad Content-Length, client disconnects mid
                # stream, ...) or in-flight counts leak and
                # least_connections starves the replica forever.
                if isinstance(lb.policy, LeastConnectionsPolicy):
                    lb.policy.acquire(target)
                try:
                    self._proxy_to(target)
                finally:
                    if isinstance(lb.policy, LeastConnectionsPolicy):
                        lb.policy.release(target)

            def _proxy_to(self, target):
                length = int(self.headers.get('Content-Length', 0))
                data = self.rfile.read(length) if length else None
                headers = {k: v for k, v in self.headers.items()
                           if k.lower() not in _HOP_HEADERS}
                try:
                    resp = requests.request(
                        self.command, target + self.path, data=data,
                        headers=headers, stream=True, timeout=300)
                except requests.RequestException as e:
                    body = f'Bad gateway: {e}'.encode()
                    self.send_response(502)
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(resp.status_code)
                for key, value in resp.headers.items():
                    if key.lower() not in _HOP_HEADERS:
                        self.send_header(key, value)
                # Stream chunks through (SSE / LLM token streams must
                # not be buffered); HTTP/1.1 + chunked framing.
                self.send_header('Transfer-Encoding', 'chunked')
                self.end_headers()
                try:
                    for chunk in resp.iter_content(chunk_size=65536):
                        if not chunk:
                            continue
                        self.wfile.write(
                            f'{len(chunk):x}\r\n'.encode())
                        self.wfile.write(chunk)
                        self.wfile.write(b'\r\n')
                    self.wfile.write(b'0\r\n\r\n')
                except (BrokenPipeError, ConnectionResetError):
                    pass

            do_GET = _proxy
            do_POST = _proxy
            do_PUT = _proxy
            do_DELETE = _proxy
            do_PATCH = _proxy
            do_HEAD = _proxy

        return Handler

    # ---------------------------------------------------------------- run

    def start(self) -> int:
        """Start proxy + sync threads; returns the bound LB port."""
        self._httpd = ThreadingHTTPServer(('0.0.0.0', self.port),
                                          self._make_handler())
        self.port = self._httpd.server_port
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        threading.Thread(target=self._sync_loop, daemon=True).start()
        logger.info(f'load balancer on :{self.port} -> '
                    f'{self.controller_url}')
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
