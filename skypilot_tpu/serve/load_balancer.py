"""Load balancer: role-aware, prefix-affine router over ready replicas.

Parity: /root/reference/sky/serve/load_balancer.py:22-205
(SkyServeLoadBalancer: syncs ready-replica URLs + reports request
timestamps to the controller every sync interval :58-111; per-request
replica pick + stream-proxy via FastAPI/httpx) and
load_balancing_policies.py.  Here the proxy is a single-threaded
asyncio server (no per-connection threads): request bodies stream to
the replica as they arrive and response bytes stream back chunk-by-
chunk with backpressure — SSE / LLM token streams are never buffered.
Policies: round_robin and least_connections (better for LLM serving,
where generation lengths make request costs wildly uneven).

Beyond the flat policies, generation traffic (`/generate*` POSTs with
a bounded JSON body) goes through `serve/router.py` — a real router:

- **role dispatch** — replicas run in prefill/decode/mixed pools
  (service_spec `roles:`); generation lands on the decode pool, and a
  prompt at/above the prefill threshold is first prefilled on a
  PREFILL replica whose KV pages are handed to the decode replica
  (`/prefill_export` -> `/kv_import`, serve/handoff.py wire format),
  so long prompts never stall in-flight decodes.  A failed handoff
  falls back to local prefill on the decode replica — never a failed
  request (chaos site `serve.kv_handoff`).
- **prefix affinity** — repeat prompt heads route to the replica whose
  prefix cache already pins those pages (TTFT collapses to the PR 7
  hit path); affinity re-pins when the replica dies.
- **backpressure retry** — an upstream 429 (`pages_exhausted` /
  `QueueFull`) retries ONCE on an alternate same-role replica,
  honoring Retry-After (bounded), instead of relaying the 429
  straight to the client.

Requests without a parseable body (streams, oversized, GET) keep the
legacy policy path untouched.
"""
from __future__ import annotations

import asyncio
import json
import math
import os
import ssl as ssl_lib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import urlsplit

import requests

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import logs as logs_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing
from skypilot_tpu.serve import brain_store as brain_store_lib
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import qos as qos_lib
from skypilot_tpu.serve import roles as roles_lib
from skypilot_tpu.serve import router as router_lib

logger = sky_logging.init_logger(__name__)

# Registry instruments (GET /metrics on the replica fronts; the LB has
# no HTTP exposition of its own yet — scrape via
# metrics.start_exposition_server when running it standalone).
_M_REQUESTS = metrics_lib.counter(
    'skytpu_lb_requests_total',
    'Requests proxied, by load-balancing policy.', ('policy',))
_M_UPSTREAM_INFLIGHT = metrics_lib.gauge(
    'skytpu_lb_upstream_inflight',
    'In-flight proxied requests per upstream replica.', ('upstream',))
_M_PROXY_LATENCY = metrics_lib.histogram(
    'skytpu_lb_proxy_seconds',
    'Client head parsed until upstream EOF relayed (includes full '
    'token streams).')
_M_NO_REPLICA = metrics_lib.counter(
    'skytpu_lb_no_replica_total',
    'Requests answered 503: no ready replicas.')
_M_UPSTREAM_ERRORS = metrics_lib.counter(
    'skytpu_lb_upstream_errors_total',
    'Requests answered 502: replica unreachable or dropped the '
    'request before any response byte.')
_M_DROPPED_TIMESTAMPS = metrics_lib.counter(
    'skytpu_lb_dropped_request_timestamps_total',
    'QPS samples dropped (oldest-first) because controller sync '
    'kept failing.')
_M_SYNC_FAILURES = metrics_lib.counter(
    'skytpu_lb_controller_sync_failures_total',
    'Controller sync attempts that failed.')
_M_SYNC_AGE = metrics_lib.gauge(
    'skytpu_lb_controller_sync_age_seconds',
    'Seconds since the last successful controller sync.  The LB keeps '
    'serving its last-known replica set while this grows (controller '
    'down != outage) — but a climbing value means the fleet view is '
    'stale and replicas will start flapping unseen.')
_M_RETIRED = metrics_lib.counter(
    'skytpu_lb_retired_total',
    'Replicas dropped via the /lb/retire drain nudge (push from the '
    'controller, ahead of the next sync).')
_M_ROUTE = metrics_lib.counter(
    'skytpu_lb_route_total',
    'Routed generation requests, by role pool and affinity outcome.',
    ('role', 'affinity'))
_M_AFFINITY_HITS = metrics_lib.counter(
    'skytpu_lb_affinity_hits_total',
    'Routed requests whose prompt prefix was pinned to a live '
    'replica.')
_M_AFFINITY_MISSES = metrics_lib.counter(
    'skytpu_lb_affinity_misses_total',
    'Routed requests with a prefix key but no live pinned replica.')
_M_RETRIES = metrics_lib.counter(
    'skytpu_lb_retries_total',
    'Requests retried on an alternate same-role replica, by reason '
    '(pages_exhausted / queue_full backpressure, upstream errors).',
    ('reason',))
_M_HANDOFF = metrics_lib.counter(
    'skytpu_lb_handoff_total',
    'KV page handoffs attempted, by outcome (ok = pages imported on '
    'the decode replica; fallback = request served via local '
    'prefill).', ('outcome',))
_M_HANDOFF_SECONDS = metrics_lib.histogram(
    'skytpu_lb_handoff_seconds',
    'prefill_export + kv_import wall time per successful handoff.')
_M_HANDOFF_WIRE_BYTES = metrics_lib.counter(
    'skytpu_lb_handoff_wire_bytes_total',
    'Bytes shipped on the kv_import leg of KV page handoffs, by wire '
    '(binary = application/octet-stream frame; json = base64 '
    'payload).', ('wire',))
# Router-tier instruments: every instance of a tier shares the process
# registry, so each series carries the instance id — `serve status
# --metrics` builds its ROUTERS table from these (scraped per instance
# via GET /lb/metrics).
_M_ROUTER_REQUESTS = metrics_lib.counter(
    'skytpu_router_requests_total',
    'Requests handled, per router-tier instance.', ('router',))
_M_ROUTER_QPS = metrics_lib.gauge(
    'skytpu_router_qps',
    'Recent requests/second per router instance (60s window, '
    'refreshed at scrape time).', ('router',))
_M_ROUTER_INFLIGHT = metrics_lib.gauge(
    'skytpu_router_inflight',
    'Requests currently in flight through this router instance.',
    ('router',))
_M_ROUTER_SYNC_AGE = metrics_lib.gauge(
    'skytpu_router_sync_age_seconds',
    'Seconds since this router instance last converged with the '
    'controller (its own sync or a /lb/state push).', ('router',))
_M_ROUTER_AFFINITY = metrics_lib.counter(
    'skytpu_router_affinity_total',
    'Prefix-affinity outcomes per router-tier instance (hit = prompt '
    'prefix pinned to a live replica; the tier-wide totals stay in '
    'skytpu_lb_affinity_*_total).', ('router', 'outcome'))
_M_ROUTER_QOS = metrics_lib.counter(
    'skytpu_router_qos_total',
    'QoS admission decisions per router instance, by class and '
    'outcome (admitted / shed).', ('router', 'qos_class', 'outcome'))
_M_ROUTER_STATE_APPLIED = metrics_lib.counter(
    'skytpu_router_state_applied_total',
    'Brain-store deltas applied from /lb/state, by kind (push = '
    'controller ready-set push; retire / affinity = sibling-router '
    'replication).', ('kind',))

_REQUEST_ID_KEY = tracing.REQUEST_ID_HEADER.lower()

# Generation endpoints the router may parse (bounded JSON bodies).
_ROUTABLE_PATHS = (http_protocol.GENERATE,
                   http_protocol.GENERATE_STREAM,
                   http_protocol.GENERATE_TEXT)


def _max_route_body() -> int:
    """Bodies above this stream through the legacy policy path instead
    of being buffered for routing."""
    return int(os.environ.get('SKYTPU_LB_ROUTE_BODY_LIMIT',
                              str(4 * 1024 * 1024)))


def _retry_max_delay() -> float:
    """Cap on how long a 429's Retry-After can hold the one in-LB
    retry (the client owns longer backoffs)."""
    return float(os.environ.get('SKYTPU_LB_RETRY_MAX_DELAY', '2'))


def _handoff_timeout() -> float:
    return float(os.environ.get('SKYTPU_LB_HANDOFF_TIMEOUT', '30'))


def _handoff_binary() -> bool:
    """Prefer the binary (octet-stream) handoff wire; '0' pins the
    legacy JSON/base64 wire.  Either way a refused binary leg falls
    back to JSON before falling back to local prefill — old replicas
    keep working mid-rollout."""
    return os.environ.get('SKYTPU_LB_HANDOFF_BINARY', '1') != '0'


def _journal_handoff(event: str, **fields: Any) -> None:
    """Journal routing/handoff events only while someone is watching
    (the `serve.kv_handoff` / `serve.rank_exec` /
    `serve.controller_tick` chaos sites armed or
    SKYTPU_SERVE_HANDOFF_EVENTS set) — the `handoff_consistency` and
    `drain_no_lost_requests` invariants replay them to prove no
    request is lost, double-executed, or routed to a retired
    replica."""
    from skypilot_tpu.chaos import injector as chaos_injector  # pylint: disable=import-outside-toplevel
    if not (os.environ.get('SKYTPU_SERVE_HANDOFF_EVENTS') or
            chaos_injector.site_armed('serve.kv_handoff') or
            chaos_injector.site_armed('serve.rank_exec') or
            chaos_injector.site_armed('serve.controller_tick')):
        return
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    try:
        events_lib.get_journal(
            os.path.join(events_lib.journal_root(),
                         'serve.jsonl')).append(event, **fields)
    except Exception:  # pylint: disable=broad-except
        pass  # recording must never break the proxy path


def _max_pending_timestamps() -> int:
    """Cap on buffered QPS samples while controller sync is failing
    (drop-oldest beyond it — the autoscaler signal degrades, the LB
    process does not)."""
    return int(os.environ.get('SKYTPU_LB_MAX_PENDING_TIMESTAMPS',
                              '100000'))

# Hop-by-hop headers never forwarded (RFC 9110 §7.6.1).  Content-Length
# and Transfer-Encoding ARE forwarded: the body bytes pass through with
# their original framing.
_HOP_HEADERS = {'connection', 'keep-alive', 'proxy-authenticate',
                'proxy-authorization', 'te', 'trailers', 'upgrade'}
_MAX_HEAD = 64 * 1024
_UPSTREAM_CONNECT_TIMEOUT = 10.0
# Max silence between upstream response chunks.  Generous because a
# busy engine can legitimately take minutes before the first token, but
# finite so a wedged replica releases the client connection (and the
# least_connections in-flight count) instead of pinning both forever.
_UPSTREAM_IDLE_TIMEOUT = float(
    os.environ.get('SKYTPU_LB_UPSTREAM_IDLE_TIMEOUT', '300'))
_CHUNK = 64 * 1024


def _lb_sync_interval() -> float:
    return float(os.environ.get('SKYTPU_LB_SYNC_INTERVAL', '20'))


def _sync_stale_warn_s() -> float:
    """Sync age past which the LB WARNs (once per outage) that it is
    serving a stale fleet view — a dead controller should be visible
    in logs and `serve status --metrics` before replicas flap."""
    return float(os.environ.get('SKYTPU_LB_SYNC_STALE_WARN_S', '90'))


def _default_deadline_ms() -> Optional[float]:
    """Fleet-wide default X-SkyTPU-Deadline-Ms the LB stamps on routed
    generation requests that carry none (None = no default)."""
    value = os.environ.get('SKYTPU_LB_DEFAULT_DEADLINE_MS')
    if not value:
        return None
    try:
        ms = float(value)
    except ValueError:
        return None
    return ms if ms > 0 else None


class LoadBalancingPolicy:

    def select(self, urls: List[str]) -> Optional[str]:
        raise NotImplementedError

    def acquire(self, url: str) -> None:  # request started
        del url

    def release(self, url: str) -> None:  # request finished (any outcome)
        del url


class RoundRobinPolicy(LoadBalancingPolicy):

    NAME = 'round_robin'

    def __init__(self) -> None:
        self._index = 0
        self._lock = threading.Lock()

    def select(self, urls: List[str]) -> Optional[str]:
        if not urls:
            return None
        with self._lock:
            url = urls[self._index % len(urls)]
            self._index += 1
        return url


class LeastConnectionsPolicy(LoadBalancingPolicy):
    """Pick the replica with the fewest in-flight requests."""

    NAME = 'least_connections'

    def __init__(self) -> None:
        self._inflight: Dict[str, int] = {}
        self._lock = threading.Lock()

    def select(self, urls: List[str]) -> Optional[str]:
        if not urls:
            return None
        with self._lock:
            return min(urls, key=lambda u: (self._inflight.get(u, 0), u))

    def acquire(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1

    def release(self, url: str) -> None:
        with self._lock:
            n = self._inflight.get(url, 0) - 1
            if n <= 0:
                self._inflight.pop(url, None)
            else:
                self._inflight[url] = n


POLICIES = {
    RoundRobinPolicy.NAME: RoundRobinPolicy,
    LeastConnectionsPolicy.NAME: LeastConnectionsPolicy,
}


def make_policy(name: Optional[str]) -> LoadBalancingPolicy:
    if name is None:
        return RoundRobinPolicy()
    if name not in POLICIES:
        raise ValueError(f'Unknown load_balancing_policy {name!r}; '
                         f'have {sorted(POLICIES)}')
    return POLICIES[name]()


class _HeadTooLarge(Exception):
    pass


async def _read_head(reader: asyncio.StreamReader) -> bytes:
    # The server's StreamReader limit is 2 * _MAX_HEAD, so readuntil
    # raising LimitOverrunError IS the too-large signal.
    try:
        return await reader.readuntil(b'\r\n\r\n')
    except asyncio.LimitOverrunError as e:
        raise _HeadTooLarge() from e


def _parse_head(head: bytes) -> Tuple[str, List[Tuple[str, str]]]:
    """Returns (start_line, [(name, value), ...])."""
    lines = head.decode('latin-1').split('\r\n')
    headers = []
    for line in lines[1:]:
        if not line:
            continue
        name, _, value = line.partition(':')
        headers.append((name.strip(), value.strip()))
    return lines[0], headers


def _body_framing(headers: List[Tuple[str, str]]) -> Tuple[str, int]:
    """('length', N) | ('chunked', 0) | ('none', 0)."""
    for name, value in headers:
        lname = name.lower()
        if lname == 'transfer-encoding' and 'chunked' in value.lower():
            return 'chunked', 0
        if lname == 'content-length':
            try:
                return 'length', int(value)
            except ValueError:
                return 'none', 0
    return 'none', 0


async def _relay_body(reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter,
                      framing: Tuple[str, int]) -> None:
    """Stream a message body with its original framing preserved.

    Every read AND drain is idle-bounded: a replica that stops READING
    the request body wedges `drain()` (send buffers full) exactly like
    one that stops writing the response — both must release the client
    connection and the in-flight count, not pin them forever.
    """

    def _bounded(awaitable):
        return asyncio.wait_for(awaitable, timeout=_UPSTREAM_IDLE_TIMEOUT)

    kind, length = framing
    if kind == 'length':
        remaining = length
        while remaining > 0:
            chunk = await _bounded(reader.read(min(_CHUNK, remaining)))
            if not chunk:
                raise ConnectionError('body truncated')
            writer.write(chunk)
            await _bounded(writer.drain())
            remaining -= len(chunk)
    elif kind == 'chunked':
        # Pass chunks through verbatim while tracking the framing so we
        # know where the body ends (incl. the trailing CRLF / trailers).
        while True:
            size_line = await _bounded(reader.readline())
            writer.write(size_line)
            try:
                size = int(size_line.strip().split(b';')[0], 16)
            except ValueError as e:
                raise ConnectionError(f'bad chunk size {size_line!r}') from e
            if size == 0:
                # Trailers (if any) end with an empty line.
                while True:
                    trailer = await _bounded(reader.readline())
                    writer.write(trailer)
                    if trailer in (b'\r\n', b'\n', b''):
                        break
                await _bounded(writer.drain())
                return
            remaining = size + 2  # chunk data + CRLF
            while remaining > 0:
                chunk = await _bounded(
                    reader.read(min(_CHUNK, remaining)))
                if not chunk:
                    raise ConnectionError('chunk truncated')
                writer.write(chunk)
                remaining -= len(chunk)
            await _bounded(writer.drain())


async def _relay_until_eof(reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
    while True:
        chunk = await asyncio.wait_for(reader.read(_CHUNK),
                                       timeout=_UPSTREAM_IDLE_TIMEOUT)
        if not chunk:
            return
        writer.write(chunk)
        # Backpressure (never buffer a token stream) but idle-bounded:
        # a client that stops READING (zero receive window) must not
        # pin the replica connection + in-flight count any more than a
        # replica that stops writing.
        await asyncio.wait_for(writer.drain(),
                               timeout=_UPSTREAM_IDLE_TIMEOUT)


class _UpstreamError(Exception):
    """Failure before any response byte was relayed → client gets 502."""


def _simple_response(status: int, reason: str, body: bytes) -> bytes:
    return (f'HTTP/1.1 {status} {reason}\r\n'
            f'Content-Length: {len(body)}\r\n'
            f'Content-Type: text/plain\r\n'
            f'Connection: close\r\n\r\n').encode() + body


class SkyServeLoadBalancer:
    """Streams requests to replicas; reports QPS to the controller."""

    def __init__(self, controller_url: str, port: int = 0,
                 policy: Optional[LoadBalancingPolicy] = None,
                 router: Optional[router_lib.Router] = None,
                 router_id: Optional[str] = None,
                 qos: Optional[Dict[str, Any]] = None) -> None:
        self.controller_url = controller_url.rstrip('/')
        self.port = port
        self.policy = policy or RoundRobinPolicy()
        # Role/affinity routing for generation requests; non-routable
        # traffic keeps the flat policy above.
        self.router = router or router_lib.Router()
        # Identity within a router tier; defaults to 'r<port>' once the
        # port is bound (the skytpu_router_* metric label).
        self.router_id = router_id
        # QoS weighted admission: per-class in-flight caps derived from
        # the class weights and this instance's in-flight bound
        # (service spec `routers.qos` / SKYTPU_LB_QOS_MAX_INFLIGHT);
        # a class over its share is shed with 429 + Retry-After.
        self.qos_specs = qos_lib.from_config(qos)
        self.qos_max_inflight = qos_lib.router_max_inflight()
        self._qos_inflight: Dict[str, int] = {}
        # Worst ready-replica median queue wait (seconds) from the last
        # controller sync; None until a replica reports a histogram.
        self._queue_wait_p50: Optional[float] = None
        # Rolling per-instance request timestamps (60s) for the
        # skytpu_router_qps gauge, refreshed at scrape time.
        self._recent_requests: List[float] = []
        self._inflight_here = 0
        # LB-side trace segments (one per routed request: route /
        # handoff / per-attempt phases), exported via GET /lb/spans
        # for cross-process assembly (sky serve trace).
        self.spans = tracing.SegmentStore()
        self.ready_urls: List[str] = []
        self.request_timestamps: List[float] = []
        # Per-role QPS samples (the controller autoscales each role
        # pool independently); same drop-oldest bound as above.
        self.role_request_timestamps: Dict[str, List[float]] = {}
        self.dropped_timestamps = 0
        self._sync_failures = 0       # consecutive; reset on success
        self._next_failure_warn = 1   # exponential-backoff WARNING
        # Controller liveness view: when the last sync succeeded (the
        # skytpu_lb_controller_sync_age_seconds gauge), and whether
        # the once-per-outage staleness WARNING already fired.
        self._last_sync_ok = time.monotonic()
        self._stale_warned = False
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()

    # ------------------------------------------------------ controller sync

    def set_replicas(self, replicas: List[Dict[str, Any]]) -> None:
        """Install the ready set with role/load info (what the
        controller sync delivers; tests and benches call it directly).
        Dicts carry at least `url`, optionally `role`, `load`,
        `page_size`, `region`."""
        endpoints = [router_lib.ReplicaEndpoint(
            url=r['url'], role=roles_lib.role_of(r),
            load=float(r.get('load') or 0.0),
            page_size=r.get('page_size'),
            region=r.get('region')) for r in replicas]
        self.router.set_endpoints(endpoints)
        # Congestion-aware shed backoff: the worst median admission
        # wait across the ready pool (seconds, from each engine's
        # queue-wait histogram) drives the 429 Retry-After stamp.
        p50s = [float(r['queue_wait_p50']) for r in replicas
                if r.get('queue_wait_p50') is not None]
        with self._lock:
            self.ready_urls = [e.url for e in endpoints]
            self._queue_wait_p50 = max(p50s) if p50s else None

    def shed_retry_after_s(self) -> int:
        """Retry-After (whole seconds) stamped on QoS sheds: the worst
        ready-replica median queue wait when the fleet reports one
        (rounded UP so the stamp never understates the wait, floor 1s
        — Retry-After is integer seconds on the wire), else the static
        default of 1s.  This is what makes batch backoff track real
        engine congestion instead of hammering a loaded fleet once a
        second."""
        with self._lock:
            p50 = self._queue_wait_p50
        if p50 is None or p50 <= 0:
            return 1
        return max(1, int(math.ceil(p50)))

    def sync_age(self) -> float:
        """Seconds since the last successful controller sync (also the
        skytpu_lb_controller_sync_age_seconds gauge)."""
        age = time.monotonic() - self._last_sync_ok
        _M_SYNC_AGE.set(round(age, 3))
        return age

    @property
    def _retired(self) -> Dict[str, int]:
        """The shared retired set (url -> epoch) — lives in the brain
        store so every router instance of a tier sees one view."""
        return self.router.store.retired_urls()

    def retire_url(self, url: str, epoch: Optional[int] = None,
                   replicated: bool = False) -> bool:
        """Drop one replica from routing NOW (the controller's drain
        nudge — ahead of the next sync): removed from the ready set
        and the router, prefix-affinity pins re-home, and the retire
        is recorded in the shared brain store at `epoch` — a sync
        captured before that epoch cannot re-add the replica on THIS
        router or any sibling (the store fans the delta out;
        `replicated` marks a delta that arrived from a sibling and
        must not fan back)."""
        store = self.router.store
        if isinstance(store, brain_store_lib.ReplicatedBrainStore):
            epoch = store.retire(url, epoch, replicated=replicated)
        else:
            epoch = store.retire(url, epoch)
        with self._lock:
            present = url in self.ready_urls
            if present:
                self.ready_urls = [u for u in self.ready_urls
                                   if u != url]
        removed = self.router.remove_endpoint(url)
        if present or removed:
            _M_RETIRED.inc()
        _journal_handoff('lb_retire', url=url, epoch=epoch,
                         known=bool(present or removed))
        logger.info(f'LB retired replica {url} (drain nudge, '
                    f'epoch {epoch})')
        return present or removed

    def _sync_with_controller(self) -> None:
        with self._lock:
            timestamps, self.request_timestamps = \
                self.request_timestamps, []
            role_timestamps, self.role_request_timestamps = \
                self.role_request_timestamps, {}
        try:
            resp = requests.post(
                self.controller_url + http_protocol.CONTROLLER_SYNC,
                json={'request_timestamps': timestamps,
                      'role_request_timestamps': role_timestamps},
                timeout=5)
            data = resp.json()
            urls = data.get('ready_replica_urls', [])
            infos = data.get('ready_replicas')
            # Epoch-guarded retired reconciliation: an entry retired at
            # epoch e only clears once the controller's view is stamped
            # retired_epoch >= e.  A stale sync — captured before a
            # sibling router's retire, arriving here late — still lists
            # the url but carries an older epoch, so it keeps being
            # filtered instead of resurrecting the replica.
            urls = self.router.store.reconcile_retired(
                urls, data.get('retired_epoch'))
            retired = set(self.router.store.retired_urls())
            if infos is not None:
                self.set_replicas([i for i in infos
                                   if i.get('url') not in retired])
            with self._lock:
                self.ready_urls = urls if infos is None else \
                    self.ready_urls
                if self._sync_failures:
                    logger.info(
                        f'LB sync recovered after '
                        f'{self._sync_failures} failed attempt(s)')
                self._sync_failures = 0
                self._next_failure_warn = 1
                self._last_sync_ok = time.monotonic()
                self._stale_warned = False
            _M_SYNC_AGE.set(0.0)
        except (requests.RequestException, ValueError) as e:
            # The samples go back on the (bounded) buffer so a
            # transient controller outage doesn't lose the QPS signal.
            with self._lock:
                self.request_timestamps = (timestamps +
                                           self.request_timestamps)
                for role, samples in role_timestamps.items():
                    self.role_request_timestamps[role] = (
                        samples +
                        self.role_request_timestamps.get(role, []))
                self._trim_timestamps_locked()
                self._sync_failures += 1
                failures = self._sync_failures
                warn = failures >= self._next_failure_warn
                if warn:
                    self._next_failure_warn = max(
                        2, self._next_failure_warn * 2)
            _M_SYNC_FAILURES.inc()
            age = self.sync_age()
            warn_stale = False
            if age > _sync_stale_warn_s():
                # _stale_warned is written under the lock everywhere
                # (sync success resets it there); claiming the
                # once-per-outage warning lock-free would let two
                # failing syncs both claim it.
                with self._lock:
                    if not self._stale_warned:
                        self._stale_warned = True
                        warn_stale = True
            if warn_stale:
                # Once per outage (reset on recovery), distinct from
                # the per-attempt backoff below: the fleet view is now
                # officially stale — last-known replicas keep serving,
                # but new/retired replicas are invisible to this LB.
                logger.warning(
                    f'LB fleet view is STALE: no successful controller '
                    f'sync for {age:.0f}s (> {_sync_stale_warn_s():.0f}s'
                    f'); serving the last-known replica set')
            # WARNING with exponential backoff (attempt 1, 2, 4, 8,
            # ...), DEBUG otherwise: a controller that is down for an
            # hour must not emit 180 identical warnings.
            if warn:
                logger.warning(
                    f'LB sync failed ({failures} consecutive): {e}')
            else:
                logger.debug(f'LB sync failed ({failures}): {e}')

    def _trim_timestamps_locked(self) -> None:
        """Drop-oldest beyond the cap (call with self._lock held)."""
        cap = _max_pending_timestamps()
        overflow = len(self.request_timestamps) - cap
        if overflow > 0:
            del self.request_timestamps[:overflow]
            self.dropped_timestamps += overflow
            _M_DROPPED_TIMESTAMPS.inc(overflow)
        for samples in self.role_request_timestamps.values():
            extra = len(samples) - cap
            if extra > 0:
                del samples[:extra]

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_with_controller()
            self._stop.wait(_lb_sync_interval())

    # -------------------------------------------------------------- proxy

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        target = None
        tracked = False
        try:
            head = await asyncio.wait_for(_read_head(reader), timeout=60)
            start_line, headers = _parse_head(head)
            t_start = time.perf_counter()
            with self._lock:
                self.request_timestamps.append(time.time())
                self._recent_requests.append(time.time())
                self._inflight_here += 1
                tracked = True
                self._trim_timestamps_locked()
                urls = list(self.ready_urls)
            _M_ROUTER_REQUESTS.labels(
                router=self.router_id or 'r0').inc()
            # Keep the router's endpoint set in lockstep with however
            # ready_urls was installed (controller sync, set_replicas,
            # or a test assigning the attribute directly).
            self.router.ensure_urls(urls)
            _M_REQUESTS.labels(policy=getattr(
                self.policy, 'NAME', type(self.policy).__name__)).inc()
            # Generation POSTs with a bounded JSON body go through the
            # role/affinity router (and can be retried/handed off —
            # the body is replayable).  Everything else streams through
            # the legacy policy path.
            parts = start_line.split(' ')
            method = parts[0] if parts else ''
            path = (parts[1].split('?', 1)[0] if len(parts) > 1 else '')
            framing = _body_framing(headers)
            if path.startswith(http_protocol.LB_PREFIX):
                # LB control plane (never proxied): the controller's
                # drain nudge and the LB's own metrics exposition.
                query = (parts[1].split('?', 1)[1]
                         if len(parts) > 1 and '?' in parts[1] else '')
                await self._handle_control(writer, method, path,
                                           reader, framing, query)
                return
            if (method == 'POST' and path in _ROUTABLE_PATHS and
                    framing[0] == 'length' and
                    framing[1] <= _max_route_body()):
                body = await asyncio.wait_for(
                    reader.readexactly(framing[1]), timeout=60)
                await self._handle_routed(writer, start_line, headers,
                                          body, t_start)
                return
            target = self.policy.select(urls)
            if target is None:
                _M_NO_REPLICA.inc()
                writer.write(_simple_response(
                    503, 'Service Unavailable', b'No ready replicas.'))
                await writer.drain()
                return
            # acquire/release bracket EVERYTHING that can raise (bad
            # framing, disconnects mid-stream) or in-flight counts leak
            # and least_connections starves the replica forever.
            self.policy.acquire(target)
            inflight = _M_UPSTREAM_INFLIGHT.labels(upstream=target)
            inflight.inc()
            try:
                await self._proxy_to(target, reader, writer, start_line,
                                     headers)
                _M_PROXY_LATENCY.observe(time.perf_counter() - t_start)
            finally:
                inflight.dec()
                self.policy.release(target)
        except _HeadTooLarge:
            try:
                writer.write(_simple_response(
                    431, 'Request Header Fields Too Large',
                    b'Request head exceeds limit.'))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
        except _UpstreamError as e:
            # No response byte was relayed yet — a 502 is still clean.
            _M_UPSTREAM_ERRORS.inc()
            try:
                writer.write(_simple_response(
                    502, 'Bad Gateway', f'Bad gateway: {e}'.encode()))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError, ValueError, OSError):
            # Client went away or the stream broke mid-relay: close.
            pass
        finally:
            if tracked:
                with self._lock:
                    self._inflight_here -= 1
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    # ----------------------------------------------------- control plane

    def _update_router_gauges(self) -> None:
        """Refresh this instance's skytpu_router_* gauges (called at
        /lb/metrics scrape time)."""
        now = time.time()
        with self._lock:
            self._recent_requests = [t for t in self._recent_requests
                                     if now - t <= 60.0]
            qps = len(self._recent_requests) / 60.0
            inflight = self._inflight_here
        rid = self.router_id or 'r0'
        _M_ROUTER_QPS.labels(router=rid).set(round(qps, 4))
        _M_ROUTER_INFLIGHT.labels(router=rid).set(inflight)
        _M_ROUTER_SYNC_AGE.labels(router=rid).set(
            round(time.monotonic() - self._last_sync_ok, 3))

    def apply_state(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Apply a POST /lb/state payload — the generalized control
        plane a router tier converges through:

        - `{'ready': [infos], 'retired_epoch': E}` — the controller's
          ready-set push (same shape as its sync response), delivered
          to every instance the moment the fleet changes.
        - `{'retire': {'url', 'epoch'}}` — a sibling router's
          replicated retirement (never re-fanned).
        - `{'affinity': {'key', 'url'}}` — a sibling's replicated
          prefix pin, so a repeat prefix re-homes identically on
          every instance."""
        applied: List[str] = []
        store = self.router.store
        infos = payload.get('ready')
        if isinstance(infos, list):
            urls = [i.get('url') for i in infos
                    if isinstance(i, dict) and i.get('url')]
            urls = store.reconcile_retired(
                urls, payload.get('retired_epoch'))
            keep = set(urls)
            self.set_replicas([i for i in infos
                               if isinstance(i, dict) and
                               i.get('url') in keep])
            with self._lock:
                self._last_sync_ok = time.monotonic()
                self._stale_warned = False
            _M_ROUTER_STATE_APPLIED.labels(kind='push').inc()
            applied.append('ready')
        retire = payload.get('retire')
        if isinstance(retire, dict) and retire.get('url'):
            self.retire_url(str(retire['url']), retire.get('epoch'),
                            replicated=True)
            _M_ROUTER_STATE_APPLIED.labels(kind='retire').inc()
            applied.append('retire')
        affinity = payload.get('affinity')
        if isinstance(affinity, dict) and affinity.get('url'):
            key = brain_store_lib.decode_affinity_key(
                affinity.get('key'))
            if key is not None:
                if isinstance(store,
                              brain_store_lib.ReplicatedBrainStore):
                    store.record_affinity(key, affinity['url'],
                                          replicated=True)
                else:
                    store.record_affinity(key, affinity['url'])
                _M_ROUTER_STATE_APPLIED.labels(kind='affinity').inc()
                applied.append('affinity')
        return {'applied': applied}

    async def _handle_control(self, writer: asyncio.StreamWriter,
                              method: str, path: str,
                              reader: asyncio.StreamReader,
                              framing: Tuple[str, int],
                              query: str = '') -> None:
        """`/lb/*` endpoints served by the LB itself:

        POST /lb/retire {"url": ..., "epoch": ...} — the controller's
        drain nudge: stop routing to the replica NOW instead of at the
        next sync; the epoch guards against stale-sync resurrection.
        POST /lb/state — the router-tier state plane: controller
        ready-set pushes and sibling retire/affinity deltas
        (see apply_state).
        GET /lb/metrics — this LB process's Prometheus exposition
        (sync age, retries, handoffs); `serve status --metrics` reads
        the SYNC AGE column here.
        GET /lb/spans — this LB's trace segments (route / handoff /
        per-attempt phases), for cross-process trace assembly."""
        body = b''
        if framing[0] == 'length' and framing[1] > 0:
            body = await asyncio.wait_for(
                reader.readexactly(min(framing[1], _max_route_body())),
                timeout=30)
        if method == 'POST' and path == http_protocol.LB_RETIRE:
            try:
                parsed = json.loads(body or b'{}') or {}
                url = parsed.get('url')
            except (json.JSONDecodeError, AttributeError):
                parsed, url = {}, None
            if not url:
                writer.write(_simple_response(
                    400, 'Bad Request', b'missing "url"'))
            else:
                known = self.retire_url(str(url), parsed.get('epoch'))
                payload = json.dumps({'retired': known}).encode()
                writer.write(
                    (f'HTTP/1.1 200 OK\r\n'
                     f'Content-Type: application/json\r\n'
                     f'Content-Length: {len(payload)}\r\n'
                     f'Connection: close\r\n\r\n').encode() + payload)
        elif method == 'POST' and path == http_protocol.LB_STATE:
            try:
                state = json.loads(body or b'{}') or {}
            except (json.JSONDecodeError, AttributeError):
                state = None
            if not isinstance(state, dict):
                writer.write(_simple_response(
                    400, 'Bad Request', b'expected a JSON object'))
            else:
                payload = json.dumps(self.apply_state(state)).encode()
                writer.write(
                    (f'HTTP/1.1 200 OK\r\n'
                     f'Content-Type: application/json\r\n'
                     f'Content-Length: {len(payload)}\r\n'
                     f'Connection: close\r\n\r\n').encode() + payload)
        elif method == 'GET' and path == http_protocol.LB_METRICS:
            self.sync_age()   # freshen the gauge at scrape time
            self._update_router_gauges()
            text = metrics_lib.expose().encode()
            writer.write(
                (f'HTTP/1.1 200 OK\r\n'
                 f'Content-Type: {metrics_lib.CONTENT_TYPE}\r\n'
                 f'Content-Length: {len(text)}\r\n'
                 f'Connection: close\r\n\r\n').encode() + text)
        elif method == 'GET' and path == http_protocol.LB_SPANS:
            payload = json.dumps({'segments': self.spans.export(
                **tracing.parse_span_query(query))}).encode()
            writer.write(
                (f'HTTP/1.1 200 OK\r\n'
                 f'Content-Type: application/json\r\n'
                 f'Content-Length: {len(payload)}\r\n'
                 f'Connection: close\r\n\r\n').encode() + payload)
        elif method == 'GET' and path == http_protocol.LB_LOGS:
            # This LB process's structured log ring, seq-paginated
            # (sky serve logs fans it in next to the replica rings).
            payload = json.dumps({'records': logs_lib.get_ring().export(
                **logs_lib.parse_log_query(query))}).encode()
            writer.write(
                (f'HTTP/1.1 200 OK\r\n'
                 f'Content-Type: application/json\r\n'
                 f'Content-Length: {len(payload)}\r\n'
                 f'Connection: close\r\n\r\n').encode() + payload)
        else:
            writer.write(_simple_response(
                404, 'Not Found', b'unknown LB control path'))
        route = path if path in http_protocol.LB_PATHS else 'unknown'
        logs_lib.access_log(logger, method, route,
                            200 if route != 'unknown' else 404)
        await writer.drain()

    # ------------------------------------------------------ routed path

    @staticmethod
    def _parse_prompt(body: bytes):
        """(request_json, prompt_ids | None, prefix_key, prompt_len)
        from a generation body.  Unparseable bodies route with no key
        (plain least-loaded in the decode pool)."""
        try:
            req = json.loads(body or b'{}')
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None, None, None, 0
        if not isinstance(req, dict):
            return None, None, None, 0
        ids = None
        prompt = req.get('prompt_ids')
        if (isinstance(prompt, list) and prompt and
                isinstance(prompt[0], list)):
            if len(prompt) == 1:
                ids = prompt[0]
        elif isinstance(prompt, list):
            ids = prompt
        if ids is not None:
            try:
                ids = [int(t) for t in ids]
            except (TypeError, ValueError):
                ids = None
        if ids:
            return req, ids, router_lib.prompt_key(prompt_ids=ids), \
                len(ids)
        text = req.get('prompt')
        if isinstance(text, str) and text:
            # ~4 chars per token: only the threshold comparison needs
            # it, so a rough estimate is fine.
            return req, None, router_lib.prompt_key(text=text), \
                len(text) // 4 + 1
        return req, None, None, 0

    def _record_role_timestamp(self, role: str) -> None:
        with self._lock:
            self.role_request_timestamps.setdefault(
                role, []).append(time.time())
            self._trim_timestamps_locked()

    async def _handle_routed(self, cwriter: asyncio.StreamWriter,
                             start_line: str,
                             headers: List[Tuple[str, str]],
                             body: bytes, t_start: float) -> None:
        """Route one buffered generation request: role dispatch +
        prefix affinity + (for prefill-heavy prompts) KV handoff, with
        one bounded same-role retry on upstream 429 backpressure.

        The whole life of the request on this LB is recorded as one
        trace segment (route / handoff / per-attempt phases) in
        self.spans, and each upstream try is stamped with
        X-SkyTPU-Attempt so the replicas' spans stay distinct when a
        retry reuses the request id."""
        wall_start = time.time()
        rid = next((v for n, v in headers
                    if n.lower() == _REQUEST_ID_KEY), None) or \
            tracing.new_request_id()
        # QoS class: the client's header, clamped to a known class
        # (absent/unknown -> the default class).
        qos_class = qos_lib.normalize(next(
            (v for n, v in headers
             if n.lower() == router_lib.QOS_CLASS_HEADER.lower()),
            None))
        router_label = self.router_id or 'r0'
        # Weighted admission: near the in-flight cap each class only
        # gets its weighted share; over it the request is shed with
        # 429 + Retry-After (the class's own backlog must not consume
        # the other class's floor).
        limits = qos_lib.admission_limits(self.qos_max_inflight,
                                          self.qos_specs)
        with self._lock:
            limit = limits.get(qos_class)
            shed = (limit is not None and
                    self._qos_inflight.get(qos_class, 0) >= limit)
            if not shed:
                self._qos_inflight[qos_class] = \
                    self._qos_inflight.get(qos_class, 0) + 1
        spec = self.qos_specs.get(qos_class)
        _journal_handoff('qos_request_start', request_id=rid,
                         qos_class=qos_class,
                         weight=spec.weight if spec else 1,
                         shed_limit=limits.get(qos_class))
        if shed:
            _M_ROUTER_QOS.labels(router=router_label,
                                 qos_class=qos_class,
                                 outcome='shed').inc()
            _journal_handoff('qos_request_end', request_id=rid,
                             qos_class=qos_class, status='shed')
            body_text = (f'QoS class {qos_class} over its admission '
                         f'share; retry later.').encode()
            cwriter.write(
                (f'HTTP/1.1 429 Too Many Requests\r\n'
                 f'Retry-After: {self.shed_retry_after_s()}\r\n'
                 f'Content-Length: {len(body_text)}\r\n'
                 f'Content-Type: text/plain\r\n'
                 f'Connection: close\r\n\r\n').encode() + body_text)
            await cwriter.drain()
            return
        _M_ROUTER_QOS.labels(router=router_label, qos_class=qos_class,
                             outcome='admitted').inc()
        qos_status = 'error'
        # Request-scoped log context for the routed leg: every record
        # the LB emits while relaying this request (routing decisions,
        # handoff legs, retries) carries the request id + process=lb.
        _log_ctx = logs_lib.bind(request_id=rid, process='lb')
        _log_ctx.__enter__()  # pylint: disable=unnecessary-dunder-call
        try:
            await self._route_admitted(cwriter, start_line, headers,
                                       body, t_start, wall_start, rid,
                                       qos_class)
            qos_status = 'ok'
        finally:
            # Access log inside the binding: the routed leg's record
            # carries request_id + process=lb for `serve logs` fan-in.
            parts = start_line.split(' ')
            req_path = (parts[1].partition('?')[0]
                        if len(parts) > 1 else '')
            logs_lib.access_log(
                logger, parts[0] if parts else '?',
                (req_path if req_path in http_protocol.REPLICA_PATHS
                 else 'unknown'),
                200 if qos_status == 'ok' else 500)
            _log_ctx.__exit__(None, None, None)
            with self._lock:
                n = self._qos_inflight.get(qos_class, 0) - 1
                if n <= 0:
                    self._qos_inflight.pop(qos_class, None)
                else:
                    self._qos_inflight[qos_class] = n
            # The qos_request lifecycle terminates on EVERY path (the
            # qos_fairness invariant replays start/end pairs).
            _journal_handoff('qos_request_end', request_id=rid,
                             qos_class=qos_class, status=qos_status)

    async def _route_admitted(self, cwriter: asyncio.StreamWriter,
                              start_line: str,
                              headers: List[Tuple[str, str]],
                              body: bytes, t_start: float,
                              wall_start: float, rid: str,
                              qos_class: str) -> None:
        """The routed path after QoS admission: role/affinity routing,
        optional KV handoff, bounded same-role retry, relay."""
        _, ids, key, prompt_len = self._parse_prompt(body)
        decision = self.router.route(key, prompt_len)
        if decision.url is None:
            _M_NO_REPLICA.inc()
            cwriter.write(_simple_response(
                503, 'Service Unavailable', b'No ready replicas.'))
            await cwriter.drain()
            return
        _M_ROUTE.labels(role=decision.role,
                        affinity=decision.affinity).inc()
        if decision.affinity == 'hit':
            _M_AFFINITY_HITS.inc()
        elif decision.affinity == 'miss':
            _M_AFFINITY_MISSES.inc()
        if decision.affinity in ('hit', 'miss'):
            _M_ROUTER_AFFINITY.labels(
                router=self.router_id or 'r0',
                outcome=decision.affinity).inc()
        self._record_role_timestamp(decision.role)
        seg: Dict[str, Any] = {
            'request_id': rid, 'process': 'lb', 'name': 'lb',
            'attempt': 0, 'start': wall_start,
            'router': self.router_id or 'r0',
            'qos_class': qos_class,
            'role': decision.role, 'affinity': decision.affinity,
            'phases': [{
                'name': 'route', 'start': wall_start,
                'duration_ms': round(
                    (time.perf_counter() - t_start) * 1e3, 3),
                'target': decision.url,
            }],
        }
        _journal_handoff('lb_route', request_id=rid, url=decision.url,
                         role=decision.role,
                         affinity=decision.affinity,
                         qos_class=qos_class,
                         router=self.router_id or 'r0',
                         region=decision.region,
                         cross_region=decision.cross_region,
                         handoff=bool(decision.handoff_source))
        handoff_ms: Optional[float] = None
        if decision.handoff_source and ids is not None:
            handoff_wall = time.time()
            handoff_ms = await self._do_handoff(decision, ids, rid)
            seg['phases'].append({
                'name': 'handoff', 'start': handoff_wall,
                'duration_ms': round(
                    handoff_ms if handoff_ms is not None else
                    (time.time() - handoff_wall) * 1e3, 3),
                'target': decision.handoff_source,
                'status': 'ok' if handoff_ms is not None
                          else 'fallback',
            })
        extra = {
            tracing.REQUEST_ID_HEADER: rid,
            router_lib.ROUTED_ROLE_HEADER: decision.role,
            router_lib.AFFINITY_HEADER: decision.affinity,
            # Stamped on every routed request (normalized — the engine
            # scheduler applies the class's token budget and deadline
            # default without re-validating).
            router_lib.QOS_CLASS_HEADER: qos_class,
        }
        if handoff_ms is not None:
            extra[router_lib.HANDOFF_MS_HEADER] = f'{handoff_ms:.3f}'
        # Fleet-default request deadline: stamped only when the client
        # sent none (the client's own budget always wins).
        default_deadline = _default_deadline_ms()
        if default_deadline is not None and not any(
                n.lower() == router_lib.DEADLINE_HEADER.lower()
                for n, _ in headers):
            extra[router_lib.DEADLINE_HEADER] = f'{default_deadline:g}'
        target: Optional[str] = decision.url
        tried: List[str] = []
        delay = 0.0
        recorded = False
        try:
            for attempt in (0, 1):
                if delay > 0:
                    # Retry-After honored, but bounded: the client owns
                    # longer backoffs, not an idle LB connection.
                    await asyncio.sleep(delay)
                next_target: Optional[str] = None
                delay = 0.0
                self.policy.acquire(target)
                self.router.acquire(target)
                inflight = _M_UPSTREAM_INFLIGHT.labels(upstream=target)
                inflight.inc()
                # Which delivery attempt this is, end to end: the
                # replica stamps it into its span (distinct segments
                # when a retry reuses the request id).
                extra[router_lib.ATTEMPT_HEADER] = str(attempt)
                attempt_phase = {'name': f'attempt-{attempt}',
                                 'start': time.time(),
                                 'target': target}
                seg['phases'].append(attempt_phase)
                seg['attempt'] = attempt
                attempt_t0 = time.perf_counter()

                def _close_attempt(status: Any) -> None:
                    attempt_phase['status'] = status
                    attempt_phase['duration_ms'] = round(
                        (time.perf_counter() - attempt_t0) * 1e3, 3)

                try:
                    tried.append(target)
                    try:
                        status, retry_after, resp_head, ureader, \
                            uwriter = await self._forward_buffered(
                                target, start_line, headers, body,
                                extra)
                    except _UpstreamError:
                        _close_attempt('upstream_error')
                        alternates = self.router.alternates(
                            target, exclude=tried)
                        if attempt == 1 or not alternates:
                            seg['status'] = 'upstream_error'
                            raise
                        # Dead/dropped replica but a replayable body:
                        # one same-role failover beats a 502.
                        _M_RETRIES.labels(reason='upstream_error').inc()
                        next_target = alternates[0]
                    else:
                        try:
                            if status == 429 and attempt == 0:
                                alternates = self.router.alternates(
                                    target, exclude=tried)
                                if alternates:
                                    # Backpressure (pages_exhausted /
                                    # queue_full): one bounded retry
                                    # on a same-role sibling beats
                                    # relaying the 429 to a client
                                    # that would retry through us
                                    # anyway.
                                    reason = (
                                        'pages_exhausted'
                                        if b'page' in resp_head.lower()
                                        else 'queue_full')
                                    _M_RETRIES.labels(
                                        reason=reason).inc()
                                    next_target = alternates[0]
                                    delay = min(retry_after,
                                                _retry_max_delay())
                            elif status >= 500 and attempt == 0:
                                # Replica-side failure (engine failed
                                # — e.g. a slice replica losing a rank
                                # mid-decode — or queue TTL expiry):
                                # the body is replayable and nothing
                                # was relayed, so one same-role
                                # sibling retry turns a dead replica's
                                # 5xx into a served request.  The
                                # controller retires the failed
                                # replica on its next probe; until
                                # then this is what "zero lost
                                # requests while the slice rebuilds"
                                # means.
                                alternates = self.router.alternates(
                                    target, exclude=tried)
                                if alternates:
                                    _M_RETRIES.labels(
                                        reason='replica_error').inc()
                                    next_target = alternates[0]
                            if next_target is None:
                                # Relay (any status): head then
                                # stream.  Record the segment NOW (the
                                # outcome is known) — a long token
                                # stream must not keep this request
                                # invisible to `sky serve trace` until
                                # the relay ends; the finally block
                                # refreshes the final duration on the
                                # same dict.
                                _close_attempt(status)
                                seg['status'] = status
                                seg['duration_ms'] = round(
                                    (time.perf_counter() - t_start) *
                                    1e3, 3)
                                self.spans.add(seg)
                                recorded = True
                                cwriter.write(resp_head)
                                await asyncio.wait_for(
                                    cwriter.drain(),
                                    timeout=_UPSTREAM_IDLE_TIMEOUT)
                                await _relay_until_eof(ureader, cwriter)
                                if status == 200:
                                    self.router.record_affinity(key,
                                                                target)
                                _M_PROXY_LATENCY.observe(
                                    time.perf_counter() - t_start)
                                _close_attempt(status)
                                return
                            _close_attempt(status)
                        finally:
                            try:
                                uwriter.close()
                                await uwriter.wait_closed()
                            except (ConnectionError, OSError):
                                pass
                finally:
                    inflight.dec()
                    self.router.release(target)
                    self.policy.release(target)
                target = next_target
        finally:
            seg['duration_ms'] = round(
                (time.perf_counter() - t_start) * 1e3, 3)
            seg.setdefault('status', 'error')
            if not recorded:
                self.spans.add(seg)

    async def _forward_buffered(self, target: str, start_line: str,
                                headers: List[Tuple[str, str]],
                                body: bytes,
                                extra: Dict[str, str]):
        """Send a fully-buffered request; returns (status, retry_after,
        response_head_bytes, ureader, uwriter) once the response head
        is in.  The caller relays or retries; raising closes nothing
        the caller holds (_UpstreamError means no connection)."""
        split = urlsplit(target)
        host = split.hostname or '127.0.0.1'
        use_tls = split.scheme == 'https'
        port = split.port or (443 if use_tls else 80)
        try:
            ureader, uwriter = await asyncio.wait_for(
                asyncio.open_connection(
                    host, port,
                    ssl=ssl_lib.create_default_context() if use_tls
                    else None),
                timeout=_UPSTREAM_CONNECT_TIMEOUT)
        except (OSError, asyncio.TimeoutError) as e:
            raise _UpstreamError(
                f'cannot reach replica {target}: {e}') from e
        try:
            skip = {n.lower() for n in extra} | _HOP_HEADERS | \
                {'host', 'expect'}
            out = [start_line]
            out.extend(f'{n}: {v}' for n, v in headers
                       if n.lower() not in skip)
            out.extend(f'{n}: {v}' for n, v in extra.items())
            out.append(f'Host: {host}:{port}')
            out.append('Connection: close')
            uwriter.write(
                ('\r\n'.join(out) + '\r\n\r\n').encode('latin-1') +
                body)
            await asyncio.wait_for(uwriter.drain(),
                                   timeout=_UPSTREAM_IDLE_TIMEOUT)
            resp_head = await asyncio.wait_for(
                ureader.readuntil(b'\r\n\r\n'),
                timeout=_UPSTREAM_IDLE_TIMEOUT)
        except (OSError, ConnectionError, asyncio.TimeoutError,
                asyncio.IncompleteReadError,
                asyncio.LimitOverrunError) as e:
            try:
                uwriter.close()
            except (ConnectionError, OSError):
                pass
            raise _UpstreamError(
                f'replica {target} dropped the request: {e}') from e
        try:
            status = int(resp_head.split(b' ', 2)[1])
        except (IndexError, ValueError) as e:
            try:
                uwriter.close()
            except (ConnectionError, OSError):
                pass
            raise _UpstreamError(
                f'replica {target} sent a malformed response') from e
        retry_after = 1.0
        for line in resp_head.decode('latin-1').split('\r\n')[1:]:
            name, _, value = line.partition(':')
            if name.strip().lower() == 'retry-after':
                try:
                    retry_after = float(value.strip())
                except ValueError:
                    pass
        return status, retry_after, resp_head, ureader, uwriter

    async def _http_request(self, target: str, path: str, body: bytes,
                            content_type: str, timeout: float,
                            accept: Optional[str] = None,
                            extra_headers: Optional[Dict[str, str]]
                            = None) -> Tuple[int, str, bytes]:
        """One bounded POST to a replica (the handoff legs); returns
        (status, response content-type, raw response body)."""
        split = urlsplit(target)
        host = split.hostname or '127.0.0.1'
        port = split.port or 80
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port),
            timeout=_UPSTREAM_CONNECT_TIMEOUT)
        try:
            accept_line = f'Accept: {accept}\r\n' if accept else ''
            extra_lines = ''.join(
                f'{k}: {v}\r\n'
                for k, v in (extra_headers or {}).items())
            writer.write((f'POST {path} HTTP/1.1\r\n'
                          f'Host: {host}:{port}\r\n'
                          f'Content-Type: {content_type}\r\n'
                          f'{accept_line}{extra_lines}'
                          f'Content-Length: {len(body)}\r\n'
                          f'Connection: close\r\n\r\n').encode() + body)
            await asyncio.wait_for(writer.drain(), timeout=timeout)
            head = await asyncio.wait_for(
                reader.readuntil(b'\r\n\r\n'), timeout=timeout)
            status = int(head.split(b' ', 2)[1])
            length = None
            resp_ctype = ''
            for line in head.decode('latin-1').split('\r\n')[1:]:
                name, _, value = line.partition(':')
                lname = name.strip().lower()
                if lname == 'content-length':
                    length = int(value.strip())
                elif lname == 'content-type':
                    resp_ctype = value.strip()
            if length is not None:
                raw = await asyncio.wait_for(reader.readexactly(length),
                                             timeout=timeout)
            else:
                raw = await asyncio.wait_for(reader.read(-1),
                                             timeout=timeout)
            return status, resp_ctype, raw
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _json_request(self, target: str, path: str,
                            payload: Dict[str, Any],
                            timeout: float,
                            extra_headers: Optional[Dict[str, str]]
                            = None) -> Tuple[int, Any]:
        """One bounded JSON POST to a replica (the handoff legs);
        returns (status, parsed body or None)."""
        status, _, raw = await self._http_request(
            target, path, json.dumps(payload).encode(),
            'application/json', timeout, extra_headers=extra_headers)
        try:
            return status, json.loads(raw or b'null')
        except json.JSONDecodeError:
            return status, None

    async def _do_handoff(self, decision: router_lib.RouteDecision,
                          prompt_ids: List[int],
                          rid: str) -> Optional[float]:
        """Prefill-replica export -> decode-replica import.  Returns
        the handoff wall time in ms, or None when any leg failed — the
        request then proceeds with LOCAL prefill on the decode replica
        (degraded latency, never a lost request).

        Wire selection: the binary octet-stream frame by default
        (SKYTPU_LB_HANDOFF_BINARY=0 pins JSON).  A replica that does
        not speak binary — an old export replying JSON, or an old
        importer 400/404-ing the frame — degrades to ONE JSON/base64
        attempt before local-prefill fallback, so mixed fleets keep
        handing off mid-rollout."""
        from skypilot_tpu.serve import handoff as handoff_lib  # pylint: disable=import-outside-toplevel
        t0 = time.perf_counter()
        _journal_handoff('kv_handoff_start', request_id=rid,
                         source=decision.handoff_source,
                         target=decision.url)
        wire = 'binary' if _handoff_binary() else 'json'
        wire_bytes = 0
        # The request id rides the handoff legs so the prefill
        # replica's export segment joins this request's trace.
        rid_header = {tracing.REQUEST_ID_HEADER: rid}
        try:
            export_req: Dict[str, Any] = {'prompt_ids': prompt_ids}
            if decision.page_size:
                export_req['page_size'] = decision.page_size
            timeout = _handoff_timeout()
            if wire == 'binary':
                export_req['wire'] = 'binary'
                status, ctype, raw = await self._http_request(
                    decision.handoff_source, http_protocol.PREFILL_EXPORT,
                    json.dumps(export_req).encode(),
                    'application/json', timeout,
                    accept=handoff_lib.CONTENT_TYPE_BINARY,
                    extra_headers=rid_header)
                if status != 200:
                    raise _UpstreamError(f'prefill_export -> {status}')
                if handoff_lib.CONTENT_TYPE_BINARY not in ctype:
                    # Old prefill replica answered JSON: import it as
                    # JSON (the payload is already in hand).
                    wire = 'json'
                    try:
                        payload = json.loads(raw or b'null')
                    except json.JSONDecodeError as e:
                        raise _UpstreamError(
                            f'prefill_export sent neither wire: {e}'
                        ) from e
                    if not isinstance(payload, dict):
                        raise _UpstreamError(
                            'prefill_export sent no payload')
                    raw = json.dumps(payload).encode()
                wire_bytes = len(raw)
                import_ctype = (handoff_lib.CONTENT_TYPE_BINARY
                                if wire == 'binary'
                                else 'application/json')
                status, _, _ = await self._http_request(
                    decision.url, http_protocol.KV_IMPORT, raw, import_ctype,
                    timeout, extra_headers=rid_header)
                if wire == 'binary' and status in (400, 404):
                    # Old decode replica (one that predates the binary
                    # wire answers 400 from its JSON parse, or 404):
                    # one JSON retry of the SAME pages before giving
                    # up on the handoff.
                    _M_RETRIES.labels(reason='handoff_wire').inc()
                    wire = 'json'
                    export_req.pop('wire', None)
                    status, payload = await self._json_request(
                        decision.handoff_source, http_protocol.PREFILL_EXPORT,
                        export_req, timeout,
                        extra_headers=rid_header)
                    if status != 200 or not isinstance(payload, dict):
                        raise _UpstreamError(
                            f'prefill_export (json retry) -> {status}')
                    raw = json.dumps(payload).encode()
                    wire_bytes = len(raw)
                    status, _ = await self._json_request(
                        decision.url, http_protocol.KV_IMPORT, payload, timeout,
                        extra_headers=rid_header)
                if status != 200:
                    raise _UpstreamError(f'kv_import -> {status}')
            else:
                status, payload = await self._json_request(
                    decision.handoff_source, http_protocol.PREFILL_EXPORT,
                    export_req, timeout, extra_headers=rid_header)
                if status != 200 or not isinstance(payload, dict):
                    raise _UpstreamError(f'prefill_export -> {status}')
                wire_bytes = len(json.dumps(payload).encode())
                status, _ = await self._json_request(
                    decision.url, http_protocol.KV_IMPORT, payload, timeout,
                    extra_headers=rid_header)
                if status != 200:
                    raise _UpstreamError(f'kv_import -> {status}')
        except (_UpstreamError, OSError, ConnectionError,
                asyncio.TimeoutError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError, ValueError) as e:
            logger.debug(f'KV handoff fell back to local prefill: {e}')
            _M_HANDOFF.labels(outcome='fallback').inc()
            _journal_handoff('kv_handoff_end', request_id=rid,
                             status='fallback', error=str(e))
            return None
        except BaseException as e:
            # Anything else (task cancellation on LB shutdown, a bug):
            # the opened kv_handoff lifecycle must still terminate or
            # the journal reads as a router that hung mid-handoff
            # (handoff_consistency would blame the wrong component).
            _journal_handoff('kv_handoff_end', request_id=rid,
                             status='error', error=str(e))
            raise
        dt = time.perf_counter() - t0
        _M_HANDOFF.labels(outcome='ok').inc()
        _M_HANDOFF_SECONDS.observe(dt)
        _M_HANDOFF_WIRE_BYTES.labels(wire=wire).inc(wire_bytes)
        _journal_handoff('kv_handoff_end', request_id=rid, status='ok',
                         duration_ms=round(dt * 1e3, 3), wire=wire,
                         wire_bytes=wire_bytes)
        return dt * 1e3

    async def _proxy_to(self, target: str, creader: asyncio.StreamReader,
                        cwriter: asyncio.StreamWriter, start_line: str,
                        headers: List[Tuple[str, str]]) -> None:
        split = urlsplit(target)
        host = split.hostname or '127.0.0.1'
        use_tls = split.scheme == 'https'
        port = split.port or (443 if use_tls else 80)
        try:
            ureader, uwriter = await asyncio.wait_for(
                asyncio.open_connection(
                    host, port,
                    ssl=ssl_lib.create_default_context() if use_tls
                    else None),
                timeout=_UPSTREAM_CONNECT_TIMEOUT)
        except (OSError, asyncio.TimeoutError) as e:
            raise _UpstreamError(f'cannot reach replica {target}: {e}') \
                from e
        try:
            # Expect: 100-continue — the client waits for our go-ahead
            # before sending the body (curl does this for large bodies);
            # answer it ourselves and strip the header upstream, since
            # we relay the body unconditionally.
            expects_continue = any(
                n.lower() == 'expect' and '100-continue' in v.lower()
                for n, v in headers)
            if expects_continue:
                cwriter.write(b'HTTP/1.1 100 Continue\r\n\r\n')
                await cwriter.drain()
            # Rewrite the head: drop hop-by-hop, pin Host, close after.
            out = [start_line]
            out.extend(f'{n}: {v}' for n, v in headers
                       if n.lower() not in _HOP_HEADERS and
                       n.lower() not in ('host', 'expect'))
            # The LB is the outermost layer: requests without an
            # X-SkyTPU-Request-Id get one here, so the replica's span
            # records and the client's response header line up
            # end to end.
            if not any(n.lower() == _REQUEST_ID_KEY
                       for n, _ in headers):
                out.append(f'{tracing.REQUEST_ID_HEADER}: '
                           f'{tracing.new_request_id()}')
            out.append(f'Host: {host}:{port}')
            out.append('Connection: close')
            try:
                uwriter.write(
                    ('\r\n'.join(out) + '\r\n\r\n').encode('latin-1'))
                await uwriter.drain()
                # Stream the request body with its original framing.
                await _relay_body(creader, uwriter, _body_framing(headers))
                # Idle timeout: a replica that accepts the connection
                # but never answers must not pin the client forever.
                first = await asyncio.wait_for(
                    ureader.read(_CHUNK), timeout=_UPSTREAM_IDLE_TIMEOUT)
            except (OSError, ConnectionError, asyncio.TimeoutError) as e:
                raise _UpstreamError(
                    f'replica {target} dropped the request: {e}') from e
            if not first:
                raise _UpstreamError(f'replica {target} sent no response')
            # Stream the response verbatim until upstream EOF: with
            # Connection: close the replica's EOF is the end marker, so
            # no response re-framing is needed and first bytes reach the
            # client as soon as the replica emits them.
            cwriter.write(first)
            await asyncio.wait_for(cwriter.drain(),
                                   timeout=_UPSTREAM_IDLE_TIMEOUT)
            await _relay_until_eof(ureader, cwriter)
        finally:
            try:
                uwriter.close()
                await uwriter.wait_closed()
            except (ConnectionError, OSError):
                pass

    # ---------------------------------------------------------------- run

    def _run_loop(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def serve():
            self._server = await asyncio.start_server(
                self._handle, '0.0.0.0', self.port, limit=2 * _MAX_HEAD)
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()
            async with self._server:
                await self._server.serve_forever()

        try:
            self._loop.run_until_complete(serve())
        except asyncio.CancelledError:
            pass
        finally:
            self._loop.close()

    def start(self) -> int:
        """Start proxy + sync threads; returns the bound LB port."""
        threading.Thread(target=self._run_loop, daemon=True).start()
        if not self._started.wait(10):
            raise RuntimeError('load balancer failed to bind')
        if self.router_id is None:
            self.router_id = f'r{self.port}'
        threading.Thread(target=self._sync_loop, daemon=True).start()
        logger.info(f'load balancer on :{self.port} -> '
                    f'{self.controller_url}')
        return self.port

    def stop(self) -> None:
        self._stop.set()
        loop, server = self._loop, self._server
        if loop is not None and server is not None:
            loop.call_soon_threadsafe(server.close)
