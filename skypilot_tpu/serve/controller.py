"""SkyServe controller: autoscaler loop + replica reconciliation + a
small HTTP control endpoint the load balancer syncs against.

Parity: /root/reference/sky/serve/controller.py:36-145
(SkyServeController: autoscaler loop :64-96; endpoints
/controller/load_balancer_sync, /update_service, /terminate_replica).
Built on stdlib ThreadingHTTPServer (no ASGI dependency; the control
plane is not a hot path — replicas serve the traffic).
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional

import requests

from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.observability import aggregator as aggregator_lib
from skypilot_tpu.observability import logs as logs_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import slo as slo_lib
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import roles as roles_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.serve_state import ServiceStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec

logger = sky_logging.init_logger(__name__)

# Autoscaler-signal gauges (observability/metrics.py): what the scaler
# saw and what it decided, per service — the "why did it scale"
# dashboard row.
_M_TARGET_REPLICAS = metrics_lib.gauge(
    'skytpu_autoscaler_target_replicas',
    'Replica target from the last scaling evaluation.', ('service',))
_M_QPS = metrics_lib.gauge(
    'skytpu_autoscaler_qps',
    'Request rate over the autoscaler QPS window.', ('service',))
_M_READY_REPLICAS = metrics_lib.gauge(
    'skytpu_autoscaler_ready_replicas',
    'Ready replicas serving traffic at evaluation time.',
    ('service',))
_M_ROLE_TARGET = metrics_lib.gauge(
    'skytpu_autoscaler_role_target_replicas',
    'Per-role-pool replica target from the last scaling evaluation '
    '(disaggregated serving: each role autoscales independently).',
    ('service', 'role'))
_M_PREFILL_SHARE = metrics_lib.gauge(
    'skytpu_serve_prefill_demand_share',
    'Windowed prefill share of fleet demand the rebalancer computed '
    'this pass (0.5 = balanced; outside the morph hysteresis band a '
    'replica changes role).', ('service',))


def _sync_interval() -> float:
    return float(os.environ.get('SKYTPU_SERVE_SYNC_INTERVAL', '20'))


def retirement_order(pool: List[Dict]) -> List[Dict]:
    """Scale-down candidate order: not-ready replicas first, then the
    NEWEST among equal status.  Newest-first matters: the oldest READY
    replica has the warmest prefix cache (the sessions the router pins
    there), so retiring it costs the most re-prefill — retire the
    replica that has accumulated the least instead."""
    return sorted(pool, key=lambda r: (
        r['status'] == ReplicaStatus.READY.value, -r['replica_id']))


class SkyServeController:

    def __init__(self, service_name: str, port: int = 0) -> None:
        self.service_name = service_name
        record = serve_state.get_service(service_name)
        assert record is not None, f'service {service_name} not in state'
        self.spec = SkyServiceSpec.from_yaml_config(record['spec'])
        self.version = record['version']
        task = task_lib.Task.from_yaml(record['task_yaml_path'])
        self.replica_manager = replica_managers.ReplicaManager(
            service_name, self.spec, task, version=self.version)
        # One autoscaler per role pool (a single 'mixed' pool without
        # `roles:` — identical to the pre-disaggregation behavior);
        # self.autoscaler stays the first pool's scaler for callers
        # that predate role pools.
        self.autoscalers = {
            role: autoscalers.make_autoscaler(self.spec, role=role)
            for role in self.spec.role_specs
        }
        self.autoscaler = next(iter(self.autoscalers.values()))
        # Fleet telemetry plane (PR 11): the controller scrapes every
        # replica's /metrics + the LB's /lb/metrics into a bounded
        # time-series store, feeds the autoscalers windowed signals,
        # computes per-replica MFU, and evaluates the spec's SLOs.
        self.aggregator = aggregator_lib.FleetAggregator(service_name)
        self.slo_tracker = slo_lib.SLOTracker(
            service_name, slo_lib.parse_slos(self.spec.slos))
        # Fleet log plane (ISSUE 19): per-replica WARN+ERROR-rate
        # spikes, journaled like SLO burn and rendered by serve top.
        self.log_spikes = logs_lib.LogSpikeTracker(service_name)
        self.port = port
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        # Last ready set pushed to the router tier (fleet-change
        # detection for _push_router_state).
        self._last_pushed_ready: Optional[List[str]] = None
        # Multi-region placement plan (optimizer.place_role_pools):
        # role -> ordered region list new replicas round-robin over.
        self.region_plan = optimizer_lib.place_role_pools(self.spec)
        self._region_cursor: Dict[str, int] = {}
        # Dynamic co-location: wall clock of the last fleet rebalance
        # pass (budget pushes + morph check run once per window).
        self._last_rebalance = 0.0

    # -------------------------------------------------------- HTTP control

    def _make_handler(self):
        controller = self

        class Handler(BaseHTTPRequestHandler):

            def log_message(self, *args):  # quiet
                del args

            def _json(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition('?')
                if path == http_protocol.CONTROLLER_SYNC:
                    self._json(200, controller.sync_payload())
                elif path == http_protocol.CONTROLLER_TELEMETRY:
                    # What `sky serve top` renders: per-role sparkline
                    # series + windowed quantiles out of the
                    # aggregator's ring buffers, SLO status, MFU, and
                    # the slowest recent traces.
                    self._json(200, controller.telemetry())
                elif path == http_protocol.CONTROLLER_LOGS:
                    self._json(
                        200, {
                            'records': logs_lib.get_ring().export(
                                **logs_lib.parse_log_query(query))
                        })
                else:
                    self._json(404, {'error': 'unknown path'})

            def do_POST(self):
                length = int(self.headers.get('Content-Length', 0))
                data = json.loads(self.rfile.read(length) or b'{}')
                if self.path == http_protocol.CONTROLLER_SYNC:
                    controller.collect_request_information(
                        data.get('request_timestamps', []),
                        data.get('role_request_timestamps') or {},
                        time.time())
                    self._json(200, controller.sync_payload())
                elif self.path == http_protocol.CONTROLLER_UPDATE:
                    controller.reload_version()
                    self._json(200, {'version': controller.version})
                elif self.path == http_protocol.CONTROLLER_TERMINATE:
                    controller.stop()
                    self._json(200, {'ok': True})
                else:
                    self._json(404, {'error': 'unknown path'})

        return Handler

    def start_http(self) -> int:
        self._httpd = ThreadingHTTPServer(('127.0.0.1', self.port),
                                          self._make_handler())
        self.port = self._httpd.server_port
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self.port

    # ------------------------------------------------------------- traffic

    def collect_request_information(self, timestamps, role_timestamps,
                                    now: float) -> None:
        """Feed the LB's QPS samples to the role pools' autoscalers.

        With per-role samples each pool sees ONLY its own traffic (a
        prefill burst scales the prefill pool, not every pool); absent
        them (an older LB) every pool sees the aggregate — the legacy
        behavior, conservative for multi-pool specs."""
        for role, scaler in self.autoscalers.items():
            if role_timestamps:
                scaler.collect_request_information(
                    role_timestamps.get(role, []), now)
            else:
                scaler.collect_request_information(timestamps, now)

    def _total_target(self) -> int:
        return sum(s.target_num_replicas
                   for s in self.autoscalers.values())

    def _next_region(self, role: str) -> Optional[str]:
        """Round-robin over the role's region plan (a multi-replica
        pool lands spread across its top regions, so a full-region
        loss leaves same-role capacity standing elsewhere)."""
        regions = self.region_plan.get(role) or []
        if not regions:
            return None
        cursor = self._region_cursor.get(role, 0)
        self._region_cursor[role] = cursor + 1
        return regions[cursor % len(regions)]

    def serving_replicas(self):
        """READY replicas with role/load/page-size facts — what the
        LB's router dispatches and hands off with."""
        urls = set(self.serving_urls())
        return [info for info in self.replica_manager.ready_infos()
                if info['url'] in urls]

    def sync_payload(self) -> Dict:
        """The /controller/load_balancer_sync response.  retired_epoch
        stamps the view: 'this ready set reflects every retirement up
        to here', so a router clears its epoch-guarded retired entries
        only once a sync provably includes them (never resurrecting a
        replica a sibling router retired moments ago)."""
        return {
            'ready_replica_urls': self.serving_urls(),
            'ready_replicas': self.serving_replicas(),
            'retired_epoch':
                replica_managers.current_retire_epoch(),
        }

    def serving_urls(self):
        """Replica URLs the LB should serve.

        rolling: every READY replica (old and new versions mix during
        an update).  blue_green: the OLD fleet keeps all traffic until
        the full NEW fleet is READY, then traffic flips to new-only in
        one step (parity: reference UpdateMode.BLUE_GREEN)."""
        if self.spec.update_mode != 'blue_green':
            return self.replica_manager.ready_urls()
        replicas = self.replica_manager.active_replicas()
        ready = [r for r in replicas
                 if r['status'] == ReplicaStatus.READY.value and r['url']]
        old_ready = [r for r in ready if r['version'] < self.version]
        new_ready = [r for r in ready if r['version'] >= self.version]
        target = self._total_target()
        if old_ready and len(new_ready) < target:
            return [r['url'] for r in old_ready]  # green not ready yet
        return [r['url'] for r in new_ready]

    # ------------------------------------------------------ rolling update

    def reload_version(self) -> None:
        record = serve_state.get_service(self.service_name)
        if record is None or record['version'] == self.version:
            return
        self.version = record['version']
        self.spec = SkyServiceSpec.from_yaml_config(record['spec'])
        task = task_lib.Task.from_yaml(record['task_yaml_path'])
        self.replica_manager.set_version(self.spec, task, self.version)
        # Keep live request history + scale target across the update
        # (a reset would collapse the blue-green flip threshold to
        # min_replicas — a capacity cliff).  Role pools carry over per
        # role; a pool new in this version starts fresh.
        new_scalers = {
            role: autoscalers.make_autoscaler(self.spec, role=role)
            for role in self.spec.role_specs
        }
        for role, scaler in new_scalers.items():
            old = self.autoscalers.get(role)
            if old is not None:
                scaler.carry_over(old)
        self.autoscalers = new_scalers
        self.autoscaler = next(iter(self.autoscalers.values()))
        # SLO objectives may have changed with the spec; breach state
        # resets with them (a new objective starts clean).  The
        # telemetry store itself carries over — history survives.
        self.slo_tracker = slo_lib.SLOTracker(
            self.service_name, slo_lib.parse_slos(self.spec.slos))
        self.region_plan = optimizer_lib.place_role_pools(self.spec)
        logger.info(f'service {self.service_name} updated to '
                    f'version {self.version}')

    def _replace_outdated(self) -> None:
        """Retire old-version replicas per the spec's update mode.

        rolling (parity: reference UpdateMode.ROLLING): at most one
        outdated replica per pass, and only when a newer-version
        replica is READY to take the traffic.  blue_green (parity:
        UpdateMode.BLUE_GREEN): the old fleet is untouched until the
        FULL new fleet is READY (serving_urls flips traffic at that
        moment), then every outdated replica is retired at once."""
        replicas = self.replica_manager.active_replicas()
        outdated = [r for r in replicas if r['version'] < self.version]
        if not outdated:
            return
        draining = [r for r in outdated
                    if r['status'] == ReplicaStatus.DRAINING.value]
        pending = [r for r in outdated
                   if r['status'] != ReplicaStatus.DRAINING.value]
        current_ready = [
            r for r in replicas
            if r['version'] == self.version and
            r['status'] == ReplicaStatus.READY.value]
        current = [r for r in replicas if r['version'] == self.version]
        target = self._total_target()
        if len(current) < target:
            return  # new-version capacity still coming up
        if self.spec.update_mode == 'blue_green':
            if len(current_ready) >= target:
                for replica in pending:
                    self.replica_manager.scale_down(
                        replica['replica_id'], drain=True,
                        reason='blue_green_update')
            return
        if draining:
            # Rolling: the previously retired replica is still
            # finishing its in-flight work — one graceful exit at a
            # time keeps the capacity dip bounded to a single replica.
            return
        if current_ready and pending:
            self.replica_manager.scale_down(pending[0]['replica_id'],
                                            drain=True,
                                            reason='rolling_update')

    # ---------------------------------------------------------- main loop

    def reconcile_once(self) -> None:
        # Chaos site: raise = a crashing tick (run_loop survives it),
        # delay = a slow control plane, deny = a wedged/skipped tick —
        # the serve plane must tolerate all three (scenario
        # controller_crash_recovery).
        if chaos_injector.inject(
                'serve.controller_tick',
                service=self.service_name) is chaos_injector.DENY:
            return
        self.reload_version()
        self.replica_manager.sync()
        # Fleet telemetry scrape (interval-gated inside): replicas'
        # /metrics + /spans, the LB's /lb/metrics -> the ring-buffer
        # store the autoscalers, SLO tracker, and `sky serve top`
        # read.  Best-effort: telemetry must never wedge reconcile.
        self._scrape_fleet()
        replicas = self.replica_manager.active_replicas()
        current_version = [r for r in replicas
                           if r['version'] >= self.version]
        # Each role pool reconciles INDEPENDENTLY: its own decode-load
        # signal ((busy + queued)/slots out of the replicas' /health),
        # its own QPS slice, its own hysteresis — a prefill burst
        # grows the prefill pool without churning decode replicas.
        for role, scaler in self.autoscalers.items():
            scaler.collect_replica_load(
                self.replica_manager.ready_loads(role=role))
            # Smoothed signals override the instantaneous ones when
            # the aggregator has history (None = keep instantaneous).
            try:
                signals = self.aggregator.role_signals(role)
                scaler.collect_windowed_signals(
                    qps=signals['qps'], loads=signals['loads'])
            except Exception:  # pylint: disable=broad-except
                logger.exception('windowed-signal computation failed')
            decision = scaler.evaluate_scaling(time.time())
            _M_ROLE_TARGET.labels(service=self.service_name,
                                  role=role).set(
                decision.target_num_replicas)
            # DRAINING replicas are already on their way out: they
            # neither count toward the pool's capacity (or every pass
            # would retire one more) nor are scale-down candidates.
            pool = [r for r in current_version
                    if roles_lib.role_of(r) == role and
                    r['status'] != ReplicaStatus.DRAINING.value]
            n_active = len(pool)
            if n_active < decision.target_num_replicas:
                # Spot/on-demand mix: keep `num_ondemand` on-demand
                # replicas, the rest spot (None = as the task asked).
                # Recount per launch so a cold start fills the base
                # before going spot.
                n_ondemand = sum(1 for r in pool if not r['is_spot'])
                for _ in range(decision.target_num_replicas - n_active):
                    use_spot: Optional[bool] = None
                    if decision.num_ondemand > 0:
                        use_spot = n_ondemand >= decision.num_ondemand
                        if not use_spot:
                            n_ondemand += 1
                    self.replica_manager.scale_up(
                        use_spot=use_spot, role=role,
                        num_hosts=getattr(
                            self.spec.role_specs[role], 'num_hosts', 1),
                        region=self._next_region(role))
            elif n_active > decision.target_num_replicas:
                extra = n_active - decision.target_num_replicas
                # Retire not-ready first, then NEWEST (retirement_order
                # — the oldest replica holds the warmest prefix cache).
                # READY replicas drain gracefully; the DRAINING row is
                # excluded from the pool next pass, so the target math
                # stays stable while the drain runs.
                for replica in retirement_order(pool)[:extra]:
                    self.replica_manager.scale_down(
                        replica['replica_id'], drain=True,
                        reason='scale_down')
        # Replicas whose role pool no longer exists in the spec (a
        # roles: change) have no autoscaler to own them — retire.
        for replica in current_version:
            if roles_lib.role_of(replica) not in self.autoscalers:
                self.replica_manager.scale_down(replica['replica_id'],
                                                drain=True,
                                                reason='role_removed')
        # Dynamic co-location: recompute fractional budget splits from
        # the aggregator's windowed per-role signals and push them to
        # the fleet; morph a replica outright past the hysteresis
        # band.  Best-effort — rebalancing must never wedge reconcile.
        try:
            self._rebalance_fleet()
        except Exception:  # pylint: disable=broad-except
            logger.exception('fleet rebalance failed')
        _M_TARGET_REPLICAS.labels(service=self.service_name).set(
            self._total_target())
        _M_QPS.labels(service=self.service_name).set(
            len(self.autoscaler.request_timestamps) /
            autoscalers.QPS_WINDOW_SIZE_SECONDS)
        _M_READY_REPLICAS.labels(service=self.service_name).set(
            len(self.replica_manager.ready_urls()))
        # SLO evaluation against the aggregated store; breaches
        # journal slo_burn_start/_end and gauge skytpu_slo_breached.
        if self.slo_tracker.slos:
            try:
                self.slo_tracker.evaluate(self.aggregator.store,
                                          time.time())
            except Exception:  # pylint: disable=broad-except
                logger.exception('SLO evaluation failed')
        # Log-spike evaluation: per-replica WARN+ERROR rates from the
        # scraped skytpu_log_records_total counters; excursions journal
        # log_error_spike_start/_end.
        try:
            self.log_spikes.evaluate(self.aggregator.store, time.time())
        except Exception:  # pylint: disable=broad-except
            logger.exception('log spike evaluation failed')
        self._replace_outdated()
        self._update_service_status()
        # Push the (possibly changed) ready set to every router
        # instance — the tier hears about fleet changes immediately
        # rather than each instance on its own sync clock.
        try:
            self._push_router_state()
        except Exception:  # pylint: disable=broad-except
            logger.exception('router state push failed')

    # ---------------------------------------------- dynamic co-location

    def _dynamic_roles_enabled(self) -> bool:
        """SKYTPU_SERVE_DYNAMIC_ROLES=1/0 overrides the spec's
        `roles: {dynamic: ...}` flag (chaos/bench runs flip it without
        re-deploying the service)."""
        env = os.environ.get('SKYTPU_SERVE_DYNAMIC_ROLES')
        if env is not None and env != '':
            return env == '1'
        return bool(self.spec.dynamic_roles)

    def _rebalance_window(self) -> float:
        env = os.environ.get('SKYTPU_SERVE_REBALANCE_WINDOW_S')
        if env:
            return float(env)
        return float(self.spec.rebalance_window_s)

    def _morph_hysteresis(self) -> float:
        env = os.environ.get('SKYTPU_SERVE_MORPH_HYSTERESIS')
        if env:
            return float(env)
        return float(self.spec.morph_hysteresis)

    def _prefill_share(self) -> float:
        """Windowed prefill share of fleet demand in [0, 1]: the
        prefill pool's routed QPS plus half the mixed pool's, over the
        total (0.5 = balanced / no signal).  This one number drives
        both the fractional budget split pushed to mixed replicas and
        the morph decision."""
        try:
            sig = {role: self.aggregator.role_signals(role)
                   for role in roles_lib.ROLES}
        except Exception:  # pylint: disable=broad-except
            return 0.5
        q_prefill = sig['prefill'].get('qps') or 0.0
        q_decode = sig['decode'].get('qps') or 0.0
        q_mixed = sig['mixed'].get('qps') or 0.0
        total = q_prefill + q_decode + q_mixed
        if total <= 0:
            return 0.5
        return (q_prefill + 0.5 * q_mixed) / total

    def _rebalance_fleet(self) -> None:
        """One rebalance pass (window-gated): push the current
        fractional budget split to every READY mixed replica over
        /role_budget, then check the hysteresis band for a morph.
        Journaled as a role_rebalance_start/_end pair — the end lands
        on every exit path (try/finally); 'partial' records pushes
        that failed (unreachable replica, stale version)."""
        if not self._dynamic_roles_enabled():
            return
        now = time.time()
        if now - self._last_rebalance < self._rebalance_window():
            return
        self._last_rebalance = now
        infos = self.replica_manager.ready_infos()
        if not infos:
            return
        share = self._prefill_share()
        _M_PREFILL_SHARE.labels(service=self.service_name).set(share)
        replica_managers._journal_drain(  # pylint: disable=protected-access
            'role_rebalance_start', service=self.service_name,
            prefill_share=round(share, 4), replicas=len(infos))
        status = 'error'
        pushed = failed = 0
        try:
            version = replica_managers.next_retire_epoch()
            # Mixed replicas track the demand split fractionally;
            # clamped so neither phase is ever starved outright —
            # morphing, not budgets, is the answer past the band.
            split = min(0.9, max(0.1, share))
            for info in infos:
                if roles_lib.role_of(info) != 'mixed':
                    continue
                ok = False
                try:
                    resp = requests.post(
                        info['url'] + http_protocol.ROLE_BUDGET,
                        json={'role': 'mixed', 'split': split,
                              'version': version},
                        timeout=5)
                    ok = (resp.status_code == 200 and
                          bool(resp.json().get('applied')))
                except (requests.RequestException, ValueError):
                    ok = False
                if ok:
                    pushed += 1
                else:
                    failed += 1
            self._maybe_morph(share)
            status = 'ok' if failed == 0 else 'partial'
        finally:
            replica_managers._journal_drain(  # pylint: disable=protected-access
                'role_rebalance_end', service=self.service_name,
                status=status, prefill_share=round(share, 4),
                pushed=pushed, failed=failed)

    def _maybe_morph(self, share: float) -> None:
        """Past the hysteresis band, morph ONE replica per pass from
        the oversupplied pure pool into the starved one (bounded
        churn), respecting both pools' spec bounds — the donor pool
        never dips below its min_replicas and the target pool never
        exceeds its max_replicas, so the per-pool autoscalers don't
        fight the morph on the next tick."""
        hysteresis = self._morph_hysteresis()
        if abs(share - 0.5) <= hysteresis:
            return
        want = 'prefill' if share > 0.5 else 'decode'
        donor_role = 'decode' if want == 'prefill' else 'prefill'
        if (want not in self.autoscalers or
                donor_role not in self.autoscalers):
            return  # morphing needs both pure pools declared
        replicas = [r for r in self.replica_manager.active_replicas()
                    if r['version'] >= self.version]
        donors = [r for r in replicas
                  if roles_lib.role_of(r) == donor_role and
                  r['status'] == ReplicaStatus.READY.value]
        if not donors:
            return
        target_pool = [r for r in replicas
                       if roles_lib.role_of(r) == want and
                       r['status'] != ReplicaStatus.DRAINING.value]
        donor_spec = self.spec.role_specs.get(donor_role)
        want_spec = self.spec.role_specs.get(want)
        if (donor_spec is not None and
                len(donors) - 1 < donor_spec.min_replicas):
            return
        if (want_spec is not None and
                len(target_pool) + 1 > want_spec.max_replicas):
            return
        # Newest donor first: the oldest replica holds the warmest
        # prefix cache for its CURRENT pool — same rationale as
        # retirement_order.
        donor = retirement_order(donors)[0]
        self.replica_manager.morph_replica(donor['replica_id'], want)

    # ------------------------------------------------- fleet telemetry

    def _scrape_targets(self) -> List[Dict]:
        """READY replicas (+ every router instance) as aggregator
        scrape targets."""
        targets: List[Dict] = [
            {'url': info['url'], 'kind': 'replica',
             'replica_id': info['replica_id'],
             'role': roles_lib.role_of(info),
             'num_hosts': info.get('num_hosts') or 1}
            for info in self.replica_manager.ready_infos()]
        record = serve_state.get_service(self.service_name)
        for port in serve_state.get_router_ports(record or {}):
            targets.append({'url': f'http://127.0.0.1:{port}',
                            'kind': 'lb'})
        return targets

    def _push_router_state(self) -> None:
        """Push the ready set (+ view epoch) to every router instance
        the moment the fleet changes, instead of waiting out each
        router's own sync interval — with N routers, pull-only sync
        means N windows of stale routing per fleet change.  Best
        effort: the routers' pull sync is the backstop."""
        payload = self.sync_payload()
        ready = payload['ready_replica_urls']
        if ready == self._last_pushed_ready:
            return
        record = serve_state.get_service(self.service_name)
        ports = serve_state.get_router_ports(record or {})
        if not ports:
            self._last_pushed_ready = ready
            return
        state = {'ready': payload['ready_replicas'],
                 'retired_epoch': payload['retired_epoch']}
        for port in ports:
            try:
                requests.post(
                    f'http://127.0.0.1:{port}{http_protocol.LB_STATE}',
                    json=state, timeout=2)
            except requests.RequestException:
                pass
        self._last_pushed_ready = ready

    def _scrape_fleet(self) -> None:
        try:
            self.aggregator.maybe_scrape(self._scrape_targets())
        except Exception:  # pylint: disable=broad-except
            logger.exception('fleet telemetry scrape failed')

    def telemetry(self) -> Dict:
        """The `/controller/telemetry` payload (`sky serve top`)."""
        return {
            'service': self.service_name,
            'version': self.version,
            **self.aggregator.fleet_snapshot(
                roles=sorted(self.autoscalers)),
            'slos': self.slo_tracker.status(),
            'log_spikes': self.log_spikes.status(),
        }

    def _update_service_status(self) -> None:
        ready = self.replica_manager.ready_urls()
        active = self.replica_manager.active_replicas()
        if ready:
            status = ServiceStatus.READY
        elif active:
            status = ServiceStatus.REPLICA_INIT
        else:
            status = ServiceStatus.NO_REPLICA
        serve_state.set_service_status(self.service_name, status)

    # ---------------------------------------------------- crash recovery

    def recover_fleet(self) -> None:
        """Reconcile serve_state against reality on startup instead of
        assuming a cold fleet.  A controller crash forgets only the
        in-memory state: the replicas keep serving, the LB keeps
        routing its last-known set.  Re-adopt live replicas by probing
        their recorded URLs, resume interrupted drains (the persisted
        drain clock keeps the original timeout), and warm-start every
        role pool's autoscaler from the live count — the first
        reconcile pass after a restart must not churn the fleet."""
        replicas = self.replica_manager.active_replicas()
        adopted: List[int] = []
        lost: List[int] = []
        draining: List[int] = []
        for replica in replicas:
            status = ReplicaStatus(replica['status'])
            url = replica['url']
            if status is ReplicaStatus.DRAINING:
                # The drain monitor resumes it on the next sync pass
                # with its persisted drain_started_at.
                draining.append(replica['replica_id'])
                continue
            if status not in (ReplicaStatus.READY,
                              ReplicaStatus.NOT_READY) or not url:
                continue  # STARTING rows re-enter the probe loop as-is
            try:
                resp = requests.get(
                    url + self.spec.readiness_path,
                    timeout=self.spec.readiness_timeout_seconds)
                alive = resp.status_code == 200
            except requests.RequestException:
                alive = False
            if alive:
                adopted.append(replica['replica_id'])
                if status is not ReplicaStatus.READY:
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        ReplicaStatus.READY)
            else:
                lost.append(replica['replica_id'])
                if status is ReplicaStatus.READY:
                    # Let the normal probe/preemption path decide its
                    # fate — recovery itself never tears down.
                    serve_state.set_replica_status(
                        self.service_name, replica['replica_id'],
                        ReplicaStatus.NOT_READY)
        for role, scaler in self.autoscalers.items():
            live = [r for r in replicas
                    if r['version'] >= self.version and
                    roles_lib.role_of(r) == role and
                    r['status'] != ReplicaStatus.DRAINING.value]
            scaler.warm_start(len(live))
        replica_managers._journal_drain(  # pylint: disable=protected-access
            'controller_recovered', service=self.service_name,
            adopted=adopted, lost=lost, draining_resumed=draining)
        logger.info(
            f'controller recovered service {self.service_name}: '
            f'adopted {len(adopted)} live replica(s), '
            f'{len(draining)} drain(s) resumed, {len(lost)} '
            f'unreachable')

    def stop(self) -> None:
        self._stop.set()

    def run_loop(self) -> None:
        """Reconcile until stopped (HTTP endpoint must be started)."""
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:  # pylint: disable=broad-except
                logger.exception('controller reconcile error')
            self._stop.wait(_sync_interval())
        if self._httpd is not None:
            self._httpd.shutdown()

    def run(self) -> None:
        self.start_http()
        record = serve_state.get_service(self.service_name)
        lb_port = record.get('load_balancer_port') if record else None
        serve_state.set_service_ports(self.service_name, self.port,
                                      lb_port or 0)
        logger.info(f'controller for {self.service_name} on :{self.port}')
        self.recover_fleet()
        self.run_loop()
