"""Model server: the TPU inference path behind an HTTP endpoint.

The reference serves whatever container the user brings; this framework
also ships a native replica server wired to its own compute layer
(models/decode.py — flash-kernel prefill + jit'd KV-cache decode), so
`sky serve up` of a model is one YAML:

    run: python -m skypilot_tpu.serve.model_server --model tiny \
            --port $SKYTPU_SERVE_REPLICA_PORT

Endpoints:
  GET  /                 -> health + engine stats (readiness probe;
                            includes recent request spans)
  GET  /metrics          -> Prometheus text exposition (observability/
                            metrics.py process-global registry: engine
                            ticks, decode tokens/s, queue-wait + TTFT +
                            ITL histograms, admission rejections)
  POST /generate         -> {"prompt_ids": [[..]], "max_new_tokens": N,
                             "temperature": T, "top_k": K, "seed": S}
                            => {"tokens": [[..]], "latency_ms": ..}
                            (sampling params work under continuous
                            batching too — selection runs on device in
                            the engine tick, seeded per request; a full
                            admission queue answers 429 + Retry-After,
                            an expired queued request 503.)
  POST /generate_stream  -> SSE: data: {"token": N} per token, then
                            data: [DONE]  (continuous batching only)
  POST /generate_text    -> {"prompt": "...", "max_new_tokens": N}
                            => {"completion": "...", ...} via the
                            checkpoint's real tokenizer
                            (models/tokenizer.py) or the byte-level
                            fallback; {"stream": true} upgrades the
                            response to SSE data: {"text": "<delta>"}
                            events with UTF-8-safe incremental decode
                            (continuous batching only).

Real checkpoints: point --checkpoint-dir at a converted HF checkpoint
(models/import_weights.py) — --model auto reads its model_config.json
and the tokenizer files sitting next to it, so one directory serves
Llama/Gemma/Qwen/Mixtral releases end to end.  Without tokenizer
files the byte-level convention (UTF-8 bytes are ids, NUL is EOS)
keeps the server dependency-free.
"""
from __future__ import annotations

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import logs as logs_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing
from skypilot_tpu.serve import batching_engine as batching_engine_lib
from skypilot_tpu.serve import handoff as handoff_lib
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import qos as qos_lib
from skypilot_tpu.serve import roles as roles_lib
from skypilot_tpu.serve import router as router_lib

logger = sky_logging.init_logger(__name__)

# Requests routed by role (the LB's X-SkyTPU-Routed-Role /
# X-SkyTPU-Affinity headers) — the replica-side view of the router's
# decisions, scraped by `serve status --metrics` for the AFFINITY
# column.
_M_ROUTED = metrics_lib.counter(
    'skytpu_engine_routed_total',
    'LB-routed requests served, by routed role and affinity outcome.',
    ('role', 'affinity'))
_M_DRAIN_REJECTED = metrics_lib.counter(
    'skytpu_serve_drain_rejected_total',
    'Generation requests answered 503 because the replica is '
    'draining (the LB retries them on a sibling).')
# Process identity marker: always 1; its labels (via the registry's
# constant labels when SKYTPU_SERVE_REPLICA_ID is set) name this
# replica, so scrapers can join any series to the replica it came
# from even without target labels.
_M_PROCESS_INFO = metrics_lib.gauge(
    'skytpu_process_info',
    'Constant 1 carrying this process\'s identity labels '
    '(replica_id / role / num_hosts on serving replicas).')
# Forward-pass FLOPs per generated token: the fleet aggregator
# multiplies this by decode tokens/s and divides by the chip roofline
# for the per-replica skytpu_mfu_estimate gauge.
_M_FLOPS_PER_TOKEN = metrics_lib.gauge(
    'skytpu_engine_model_flops_per_token',
    'Approximate forward FLOPs per generated token (2 x parameter '
    'count plus the context-dependent attention term) of the model '
    'this replica serves.')
# Live weight swap + bulk inference (sky batch-infer): the replica-side
# series the fleet aggregator folds into its batch section for
# `sky serve top`.
_M_WEIGHT_SWAPS = metrics_lib.counter(
    'skytpu_batch_weight_swaps_total',
    'Live weight swaps attempted on this replica (POST /weights_swap), '
    'by outcome.', ('status',))
_M_WEIGHT_EPOCH = metrics_lib.gauge(
    'skytpu_batch_weight_epoch',
    'Weight epoch currently serving (0 = boot weights; each '
    'successful live swap bumps it).')
_M_BATCH_ROWS = metrics_lib.counter(
    'skytpu_batch_rows_served_total',
    'Generation rows served under QoS class batch — the replica-side '
    'progress signal of a bulk-inference run.')


def model_flops_per_token(cfg, n_params: int, max_len: int) -> float:
    """Forward FLOPs per generated token for the MFU roofline.

    Matmul work is ~2 x params (one multiply-add per parameter per
    token).  On top of that, attention reads the KV cache: per layer
    and cached position, QK^T and attn x V each cost
    2 x n_heads x head_dim FLOPs; at the mean decode context
    (max_len / 2) that adds 2 x n_layers x n_heads x head_dim x
    max_len.  `SKYTPU_MODEL_FLOPS_PER_TOKEN` overrides the whole
    estimate for imported models whose param tree misleads the count
    (quantized or partially-frozen checkpoints)."""
    override = os.environ.get('SKYTPU_MODEL_FLOPS_PER_TOKEN')
    if override:
        try:
            return float(override)
        except ValueError:
            logger.warning('Ignoring non-numeric '
                           f'SKYTPU_MODEL_FLOPS_PER_TOKEN={override!r}')
    attn = (2.0 * cfg.n_layers * cfg.n_heads * cfg.head_dim
            * float(max_len))
    return 2.0 * float(n_params) + attn


class ClientDisconnected(RuntimeError):
    """The client hung up while its request was in flight: the engine
    slot was cancelled and its KV pages freed; no response is owed."""


def default_deadline_ms() -> Optional[float]:
    """Replica-side default request deadline (ms) for requests that
    carry no X-SkyTPU-Deadline-Ms header; None = no deadline."""
    value = os.environ.get('SKYTPU_SERVE_DEFAULT_DEADLINE_MS')
    if not value:
        return None
    try:
        ms = float(value)
    except ValueError:
        return None
    return ms if ms > 0 else None


def _attempt_header(raw: Optional[str]) -> Optional[int]:
    """Parse the LB's X-SkyTPU-Attempt header value (None when absent
    or malformed — spans then read as attempt 0)."""
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


# `GET /spans` query parsing lives with the span stores; both HTTP
# fronts and the LB control plane share it.
parse_span_query = tracing.parse_span_query


def _maybe_journal_request(event: str, **fields) -> None:
    """Journal request execution only while someone is watching (the
    `serve.kv_handoff` / `serve.rank_exec` chaos sites armed, or
    SKYTPU_SERVE_HANDOFF_EVENTS set): the handoff_consistency
    invariant replays these to prove no request is lost or
    double-executed across a handoff failure OR a slice-rank death."""
    import os  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.chaos import injector as chaos_injector  # pylint: disable=import-outside-toplevel
    if not (os.environ.get('SKYTPU_SERVE_HANDOFF_EVENTS') or
            chaos_injector.site_armed('serve.kv_handoff') or
            chaos_injector.site_armed('serve.rank_exec') or
            chaos_injector.site_armed('serve.controller_tick')):
        return
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    try:
        events_lib.get_journal(
            os.path.join(events_lib.journal_root(),
                         'serve.jsonl')).append(event, **fields)
    except Exception:  # pylint: disable=broad-except
        pass  # recording must never break the serving path


def _maybe_journal_batch(event: str, **fields) -> None:
    """Journal the weight-swap lifecycle only while someone is watching
    (the `batch.shard_write` chaos site armed, or SKYTPU_BATCH_EVENTS
    set): the batch_exactly_once invariant replays these alongside the
    batch driver's shard/row events."""
    import os  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.chaos import injector as chaos_injector  # pylint: disable=import-outside-toplevel
    if not (os.environ.get('SKYTPU_BATCH_EVENTS') or
            chaos_injector.site_armed('batch.shard_write')):
        return
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    try:
        events_lib.get_journal(
            os.path.join(events_lib.journal_root(),
                         'serve.jsonl')).append(event, **fields)
    except Exception:  # pylint: disable=broad-except
        pass  # recording must never break the serving path


class ModelServer:

    def __init__(self, model: str, *, checkpoint_dir: Optional[str] = None,
                 max_len: int = 512, max_batch: int = 8,
                 seed: int = 0, quantize: Optional[str] = None,
                 continuous_batching: bool = False,
                 tensor: int = 1,
                 tokenizer_path: Optional[str] = None,
                 max_queue: int = 0,
                 queue_ttl: Optional[float] = None,
                 prefill_chunk: int = 512,
                 default_temperature: float = 0.0,
                 default_top_k: int = 0,
                 default_seed: int = 0,
                 kv_pages: Optional[int] = None,
                 page_size: int = 16,
                 quantize_kv: bool = False,
                 prefix_caching: bool = True,
                 spec_tokens: int = 0,
                 role: str = router_lib.DEFAULT_ROLE,
                 num_hosts: int = 1,
                 sp_threshold: Optional[int] = None,
                 slice_sequence: Optional[int] = None,
                 slice_tensor: Optional[int] = None,
                 replica_id: Optional[int] = None) -> None:
        import jax
        import flax.linen as nn

        from skypilot_tpu.models import configs
        from skypilot_tpu.models.transformer import Transformer

        if quantize not in (None, 'int8'):
            # Validate BEFORE the (potentially minutes-long) checkpoint
            # restore, not after.
            raise ValueError(f'Unknown quantize mode {quantize!r}; '
                             "have 'int8'.")
        if tensor > 1 and quantize:
            raise ValueError(
                'quantize + tensor sharding is not supported yet '
                '(quantized leaves change the param pytree the '
                'shardings were computed for).')
        self.num_hosts = int(num_hosts)
        self.sp_threshold = sp_threshold
        if self.num_hosts > 1:
            if tensor > 1:
                raise ValueError(
                    '--num-hosts subsumes --tensor: the slice mesh '
                    'lays out sequence x tensor itself '
                    '(--slice-tensor pins the factor).')
            if quantize:
                raise ValueError(
                    'quantize + multi-host sharding is not supported '
                    'yet (quantized leaves change the param pytree '
                    'the shardings were computed for).')
            if not continuous_batching:
                raise ValueError('--num-hosts > 1 requires '
                                 '--continuous-batching (the slice '
                                 'engine IS the batching engine)')
        if model == 'auto':
            # Converted checkpoints carry their own ModelConfig
            # (import_weights writes model_config.json next to the
            # orbax step) — no preset needed for real releases.
            from skypilot_tpu.models import import_weights
            cfg = (import_weights.load_model_config(checkpoint_dir)
                   if checkpoint_dir else None)
            if cfg is None:
                raise ValueError(
                    "--model auto needs --checkpoint-dir pointing at a "
                    "converted checkpoint (with model_config.json); "
                    "see python -m skypilot_tpu.models.import_weights.")
            self.cfg = cfg
        else:
            self.cfg = configs.get_config(model)
        # Real tokenizer when the checkpoint ships one (converted
        # checkpoints do); byte-level fallback otherwise.
        from skypilot_tpu.models import tokenizer as tokenizer_lib
        self.tokenizer = tokenizer_lib.load_tokenizer(
            tokenizer_path or checkpoint_dir)
        if self.tokenizer.eos_id is None:
            # stop_token=None means every request runs to
            # max_new_tokens, holding batching slots; say so once at
            # startup instead of silently degrading throughput.
            logger.warning(
                'Tokenizer has no EOS id (missing/incomplete '
                'tokenizer_config.json?): generation cannot stop '
                'early and will always run to max_new_tokens.')
        self.max_len = max_len
        self.max_batch = max_batch
        # Disaggregated serving role (prefill / decode / mixed):
        # advertised via /health so the controller and LB can dispatch
        # by role; the engine itself is role-agnostic — a prefill
        # replica mostly serves /prefill_export, a decode replica
        # mostly /kv_import + generation, and either can do both.
        if role not in router_lib.ROLES:
            raise ValueError(f'Unknown replica role {role!r}; one of '
                             f'{router_lib.ROLES}')
        self.role = role
        # Graceful drain: once set (POST /drain, from the controller's
        # retirement path), new generation work is refused with 503 +
        # Retry-After while in-flight decodes run to completion.
        self.draining = False
        # Process identity for fleet telemetry: which replica this is.
        # Explicit kwarg (tests run several servers per process), else
        # the controller-set env var (real replica processes).
        env_rid = os.environ.get('SKYTPU_SERVE_REPLICA_ID')
        if replica_id is not None:
            self.replica_id: Optional[int] = int(replica_id)
        elif env_rid and env_rid.isdigit():
            self.replica_id = int(env_rid)
        else:
            self.replica_id = None
        if env_rid and env_rid.isdigit():
            # Constant identity labels on EVERY exposed series: the
            # controller's aggregator keys its time-series store by
            # the full label set, so replicas must not expose
            # indistinguishable series.  Env-gated: only a real
            # replica process (one server per process) owns the
            # process-global registry's identity.
            metrics_lib.REGISTRY.set_const_labels({
                'replica_id': env_rid, 'role': role,
                'num_hosts': int(num_hosts)})
            # Same ownership rule for the log plane's process-level
            # identity fallback (per-request contextvar binds win).
            logs_lib.set_process_identity(
                'replica', replica_id=int(env_rid), role=role)
        _M_PROCESS_INFO.set(1)
        # Trace segments for non-engine legs of a request's life (the
        # /prefill_export and /kv_import handoff endpoints record
        # here); exported with the engine spans via GET /spans.
        self.trace_segments = tracing.SegmentStore()
        model_mod = Transformer(self.cfg)
        init_tokens = jax.numpy.zeros((1, 8), jax.numpy.int32)
        key = jax.random.PRNGKey(seed)

        # Tensor-sharded serving (models too big for one chip): params
        # carry NamedShardings over a tensor mesh; GSPMD partitions the
        # decode einsums and inserts the collectives — the decode code
        # is unchanged.
        # Request-side sampling defaults (the CLI's --temperature /
        # --top-k / --seed): applied when a request omits the field.
        self.default_temperature = float(default_temperature)
        self.default_top_k = int(default_top_k)
        self.default_seed = int(default_seed)
        self._shardings = None
        self._mesh = None
        if self.num_hosts > 1:
            # Slice replica: one mesh (sequence x tensor) over the
            # slice's hosts; weights shard per the same SpecLayout the
            # tensor path uses (heads/mlp/vocab on 'tensor', embed on
            # 'fsdp' — trivial axes resolve to replication).
            from skypilot_tpu.parallel.sharding import LOGICAL_AXIS_RULES
            from skypilot_tpu.serve import slice_replica as slice_lib
            mesh = slice_lib.build_slice_mesh(
                self.num_hosts, self.cfg, sequence=slice_sequence,
                tensor=slice_tensor)
            self._mesh = mesh
            abstract = jax.eval_shape(
                lambda rng: model_mod.init(rng, init_tokens)['params'],
                key)
            specs = nn.get_partition_spec(abstract)
            self._shardings = nn.meta.unbox(nn.logical_to_mesh_sharding(
                specs, mesh, LOGICAL_AXIS_RULES))
        elif tensor > 1:
            from skypilot_tpu.parallel import MeshConfig, build_mesh
            from skypilot_tpu.parallel.sharding import LOGICAL_AXIS_RULES
            if len(jax.devices()) < tensor:
                raise ValueError(
                    f'tensor={tensor} needs {tensor} devices; have '
                    f'{len(jax.devices())}.')
            for dim, value in (('n_kv_heads', self.cfg.n_kv_heads),
                               ('n_heads', self.cfg.n_heads),
                               ('d_ff', self.cfg.d_ff),
                               ('vocab_size', self.cfg.vocab_size)):
                if value % tensor:
                    raise ValueError(
                        f'tensor={tensor} must divide {dim} ({value}) '
                        f'for {model!r}; pick a smaller degree.')
            mesh = build_mesh(MeshConfig(tensor=tensor),
                              devices=jax.devices()[:tensor])
            self._mesh = mesh
            abstract = jax.eval_shape(
                lambda rng: model_mod.init(rng, init_tokens)['params'],
                key)
            specs = nn.get_partition_spec(abstract)
            self._shardings = nn.meta.unbox(nn.logical_to_mesh_sharding(
                specs, mesh, LOGICAL_AXIS_RULES))

        def _init(rng):
            return nn.meta.unbox(
                model_mod.init(rng, init_tokens)['params'])

        from skypilot_tpu.data import checkpoints
        if (checkpoint_dir and
                checkpoints.latest_step(checkpoint_dir) is not None):
            # Restore straight from checkpoint metadata: random weights
            # are never materialised just to be overwritten (for an 8B
            # model that would double peak memory and add minutes of
            # startup), and optimizer moments are never read at all.
            # With tensor sharding, shards stream straight to their
            # devices — the unsharded tree never exists on one chip.
            params = checkpoints.restore_params(
                checkpoint_dir, None, shardings=self._shardings)
        else:
            if checkpoint_dir:
                logger.warning(
                    f'No checkpoint under {checkpoint_dir}; serving '
                    'FRESH random-init weights.')
            else:
                logger.warning('No --checkpoint-dir given; serving '
                               'FRESH random-init weights.')
            # Init deterministically UNSHARDED, then place: generating
            # the random weights under GSPMD partitioning changes the
            # values with the mesh layout (the partitioned RNG lowers
            # differently), so a sharded replica would not be
            # weight-identical to a single-process one.  Checkpoints —
            # the real serving path — stream sharded regardless.
            params = jax.jit(_init)(key)
            if self._shardings is not None:
                params = jax.device_put(params, self._shardings)
        if quantize:
            from skypilot_tpu.models import quantize as quantize_lib
            params = quantize_lib.quantize_params(params)
            report = quantize_lib.quantization_report(params)
            logger.info(
                f'int8 weight-only quantization: '
                f'{report["quantized_bytes"] / 1e6:.1f} MB '
                f'({report["ratio"]:.2f}x of f32)')
        self.params = params
        # Live weight swap (POST /weights_swap): the epoch now serving
        # (0 = boot weights; mirrors the engine's counter) and how to
        # re-quantize swapped checkpoints when this server quantizes.
        self.weight_version = 0
        self._quantize = quantize
        # Serving roofline input: forward FLOPs per generated token.
        # The controller's aggregator turns this + decode tokens/s
        # into the per-replica skytpu_mfu_estimate gauge.
        n_params = sum(int(p.size)
                       for p in jax.tree_util.tree_leaves(params))
        self.flops_per_token = model_flops_per_token(
            self.cfg, n_params, max_len)
        _M_FLOPS_PER_TOKEN.set(self.flops_per_token)
        # One generation at a time: KV caches are sized per call and
        # the chip is exclusive anyway; the HTTP layer queues.
        self._lock = threading.Lock()
        self._engine = None
        if continuous_batching:
            # Requests join a running batch as slots free; token
            # selection (greedy or per-request temperature/top-k) runs
            # on device inside the pipelined tick.
            if self.num_hosts > 1:
                # Slice replica: coordinated ticks across the gang +
                # sequence-parallel long-context prefill.
                from skypilot_tpu.serve import slice_replica as slice_lib
                self._engine = slice_lib.SliceReplicaEngine(
                    self.cfg, self.params, num_hosts=self.num_hosts,
                    sp_threshold=sp_threshold, mesh=self._mesh,
                    max_len=max_len, slots=max_batch,
                    max_queue=max_queue, queue_ttl=queue_ttl,
                    prefill_chunk=prefill_chunk, kv_pages=kv_pages,
                    page_size=page_size, quantize_kv=quantize_kv,
                    prefix_caching=prefix_caching,
                    spec_tokens=spec_tokens)
            else:
                self._engine = batching_engine_lib.ContinuousBatchingEngine(
                    self.cfg, self.params, max_len=max_len,
                    slots=max_batch, max_queue=max_queue,
                    queue_ttl=queue_ttl, prefill_chunk=prefill_chunk,
                    mesh=self._mesh, kv_pages=kv_pages,
                    page_size=page_size, quantize_kv=quantize_kv,
                    prefix_caching=prefix_caching,
                    spec_tokens=spec_tokens)
        if self._engine is not None:
            # The engine worker thread emits records outside any HTTP
            # request context; it stamps this identity (plus the
            # request id it re-binds around each admission) so the log
            # plane can attribute worker-side lines in-process too.
            self._engine.log_identity = {
                'process': 'replica', 'replica_id': self.replica_id,
                'role': self.role}

    def close(self) -> None:
        """Release background resources (the batching engine's worker
        thread + slot KV cache); safe to call twice."""
        if self._engine is not None:
            self._engine.stop()
            self._engine = None

    def drain(self) -> Dict[str, Any]:
        """Enter draining: refuse new generates (503 + Retry-After)
        while the engine finishes what it holds.  Idempotent; returns
        the in-flight snapshot the controller's drain monitor reads."""
        self.draining = True
        return {'draining': True, 'inflight': self.inflight()}

    def apply_role_budget(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """POST /role_budget: controller rebalance push or role-morph
        commit.  Swaps the engine's fractional-role budget IN PLACE
        (warm weights and page pool untouched) and, when the payload
        names a different role, flips the advertised role and clears
        draining — the morph's scoped drain is over and the replica
        re-opens under its new role.  Version-ordered: a stale push
        (older `version` than the budget in force) is dropped so a
        rebalance racing a morph cannot resurrect the old split."""
        engine = self._engine
        if engine is None:
            raise ValueError('role budgets require --continuous-batching')
        new_role = roles_lib.normalize(req.get('role') or self.role)
        version = int(req.get('version', 0))
        split = req.get('split')
        if (req.get('prefill_tokens') is not None and
                req.get('decode_tokens') is not None):
            budget = batching_engine_lib.RoleBudget(
                prefill_tokens=int(req['prefill_tokens']),
                decode_tokens=int(req['decode_tokens']),
                role=new_role,
                split=float(split) if split is not None
                else roles_lib.DEFAULT_SPLITS[new_role],
                version=version)
        elif split is not None:
            budget = batching_engine_lib.RoleBudget.from_split(
                float(split), slots=self.max_batch,
                prefill_chunk=engine.prefill_chunk, role=new_role,
                version=version)
        else:
            budget = batching_engine_lib.RoleBudget.for_role(
                new_role, slots=self.max_batch,
                prefill_chunk=engine.prefill_chunk, version=version)
        applied = engine.set_role_budget(budget)
        morphed = applied and new_role != self.role
        if morphed:
            self.role = new_role
            self.draining = False
        elif applied and req.get('resume'):
            # Aborted morph rollback: re-open under the same role.
            self.draining = False
        return {'applied': applied, 'morphed': morphed,
                'role': self.role, 'draining': self.draining,
                'budget': budget.as_dict()}

    def weights_swap(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """POST /weights_swap: live checkpoint swap — restore the
        latest orbax checkpoint under `checkpoint_dir` and swap it
        into the running engine WITHOUT dropping the KV page pool or
        any in-flight request (the engine assigns the new tree between
        ticks — the scoped pause; see
        ContinuousBatchingEngine.swap_params).  The bumped weight
        epoch lands in /health, every later request's span, and every
        generate response, so batch output rows record which weights
        produced them."""
        from skypilot_tpu.data import checkpoints  # pylint: disable=import-outside-toplevel
        engine = self._engine
        if engine is None:
            raise ValueError('live weight swap requires '
                             '--continuous-batching')
        checkpoint_dir = req.get('checkpoint_dir')
        if not checkpoint_dir or not isinstance(checkpoint_dir, str):
            raise ValueError('weights_swap needs a checkpoint_dir')
        step = checkpoints.latest_step(checkpoint_dir)
        if step is None:
            raise ValueError(f'no checkpoint under {checkpoint_dir}')
        _maybe_journal_batch('weight_swap_start',
                             replica_id=self.replica_id,
                             checkpoint_dir=checkpoint_dir, step=step)
        t0 = time.perf_counter()
        status = 'error'
        epoch: Optional[int] = None
        try:
            params = checkpoints.restore_params(
                checkpoint_dir, None, shardings=self._shardings)
            if self._quantize:
                from skypilot_tpu.models import quantize as quantize_lib  # pylint: disable=import-outside-toplevel
                params = quantize_lib.quantize_params(params)
            epoch = engine.swap_params(params)
            self.params = params
            self.weight_version = epoch
            status = 'ok'
        finally:
            _M_WEIGHT_SWAPS.labels(status=status).inc()
            if epoch is not None:
                _M_WEIGHT_EPOCH.set(epoch)
            _maybe_journal_batch('weight_swap_end',
                                 replica_id=self.replica_id,
                                 status=status, weight_epoch=epoch)
        return {'weight_version': epoch, 'step': step,
                'restore_ms': round(
                    (time.perf_counter() - t0) * 1e3, 1)}

    def inflight(self) -> int:
        """Busy slots + queued admissions (0 without an engine): the
        occupancy signal a drain waits on, per the concurrency-limits
        framing — slot/page occupancy, not wall-clock guesses."""
        engine = self._engine
        if engine is None:
            return 0
        stats = engine.stats()
        return (int(stats.get('busy_slots', 0)) +
                int(stats.get('queued_requests', 0)))

    def identity(self) -> Dict[str, Any]:
        """Trace-segment identity tags for this replica's exports."""
        return {'process': 'replica', 'replica_id': self.replica_id,
                'role': self.role, 'num_hosts': self.num_hosts}

    def export_spans(self, since: Optional[float] = None,
                     request_id: Optional[str] = None,
                     limit: Optional[int] = None) -> Dict[str, Any]:
        """The `GET /spans` payload: engine request spans + the
        handoff-endpoint segments, identity-tagged, oldest first."""
        segments = self.trace_segments.export(
            since=since, request_id=request_id)
        engine = self._engine
        if engine is not None:
            segments.extend(engine._spans.export(  # pylint: disable=protected-access
                self.identity(), since=since, request_id=request_id))
        segments.sort(key=lambda s: s.get('start') or 0.0)
        if limit is not None:
            segments = segments[-int(limit):]
        return {'segments': segments}

    def export_profile(self) -> Dict[str, Any]:
        """The `GET /profile` payload: the engine's tick-phase ring +
        recompile-sentinel snapshot, identity-tagged so `sky serve
        profile` can stitch a fleet view."""
        payload = self.identity()
        engine = self._engine
        payload['profile'] = (engine.profile() if engine is not None
                              else None)
        return payload

    def record_handoff_segment(self, name: str, request_id: str,
                               start: float, duration_ms: float,
                               attempt: Optional[int] = None,
                               **fields: Any) -> None:
        """One non-engine leg of a request's life (the prefill
        replica's /prefill_export, the decode replica's /kv_import)
        as a trace segment — without this, `sky serve trace` of a
        disaggregated request would miss the prefill replica
        entirely (exports never create an engine span)."""
        seg = self.identity()
        seg.update({'name': name, 'request_id': request_id,
                    'start': start,
                    'duration_ms': round(duration_ms, 3),
                    'attempt': int(attempt or 0), 'phases': []})
        seg.update(fields)
        self.trace_segments.add(seg)

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 stop_token=None, seed: int = 0,
                 request_id: Optional[str] = None,
                 route_meta: Optional[Dict[str, Any]] = None,
                 deadline_ms: Optional[float] = None,
                 qos_class: Optional[str] = None,
                 on_submit=None, disconnect_probe=None) -> Any:
        """stop_token: None, a single id, or an iterable of ids (the
        tokenizer's multi-EOS stop set).

        request_id: propagated X-SkyTPU-Request-Id; under continuous
        batching it names the request's span record (multi-row batches
        suffix `-1`, `-2`, ... on rows after the first).

        deadline_ms: per-request time budget (X-SkyTPU-Deadline-Ms);
        the engine reaps the slot(s) past it -> DeadlineExceeded.

        on_submit: called with the engine request handles right after
        submission (async front's disconnect watchdog cancels through
        them).  disconnect_probe: polled while waiting; returning True
        means the client hung up — every handle is cancelled and
        ClientDisconnected raised (threaded front, MSG_PEEK probe)."""
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.models import decode
        prompt = jnp.asarray(prompt_ids, jnp.int32)
        if prompt.ndim != 2:
            raise ValueError('prompt_ids must be [batch, seq]')
        if prompt.shape[0] > self.max_batch:
            raise ValueError(
                f'batch {prompt.shape[0]} > max_batch {self.max_batch}')
        if prompt.shape[1] + max_new_tokens > self.max_len:
            raise ValueError(
                f'prompt {prompt.shape[1]} + new {max_new_tokens} '
                f'exceeds max_len {self.max_len}')
        sampling = decode.SamplingConfig(temperature=temperature,
                                         top_k=top_k, seed=seed)
        if self._engine is not None:
            # Each row is its own request: they decode TOGETHER with
            # whatever else is in flight (no lock — that is the point).
            # Sampling runs ON DEVICE inside the engine tick, seeded
            # per request.
            requests = [
                self._engine.submit([int(t) for t in row],
                                    max_new_tokens,
                                    stop_token=stop_token,
                                    sampling=sampling,
                                    request_id=(
                                        None if request_id is None
                                        else (request_id if i == 0 else
                                              f'{request_id}-{i}')),
                                    route_meta=route_meta,
                                    deadline_ms=deadline_ms,
                                    qos_class=qos_class)
                for i, row in enumerate(prompt_ids)
            ]
            if on_submit is not None:
                on_submit(requests)
            if disconnect_probe is not None:
                # Poll the connection while waiting: a client that hung
                # up must free its slots NOW, not after max_new_tokens.
                wait_until = time.monotonic() + 600
                while True:
                    pending = next((r for r in requests
                                    if not r.done.is_set()), None)
                    if pending is None:
                        break
                    if disconnect_probe():
                        for r in requests:
                            r.cancel()
                        raise ClientDisconnected(
                            'client disconnected mid-generation')
                    if time.monotonic() > wait_until:
                        raise TimeoutError('generation timed out')
                    pending.done.wait(0.1)
            return [r.result(timeout=600) for r in requests]
        with self._lock:
            tokens, new = decode.generate(
                self.cfg, self.params, prompt,
                max_new_tokens=max_new_tokens, max_len=self.max_len,
                sampling=sampling, rng=jax.random.PRNGKey(seed))
        del tokens
        return new.tolist()


def _make_handler(server: ModelServer):

    class Handler(BaseHTTPRequestHandler):
        protocol_version = 'HTTP/1.1'

        def log_message(self, *args):
            del args

        def send_response(self, code, message=None):
            # Remember the status for the access log/counter (the
            # last send_response of the exchange wins, matching what
            # actually went on the wire).
            self._status = code
            super().send_response(code, message)

        def _read_body(self) -> bytes:
            length = int(self.headers.get('Content-Length', 0))
            return self.rfile.read(length)

        def _read_json(self) -> Dict[str, Any]:
            return json.loads(self._read_body() or b'{}')

        def _reply(self, code: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_backpressure(self, e: Exception) -> bool:
            """Admission-control errors become honest HTTP status +
            Retry-After instead of a generic 500: 429 when the queue is
            full, 503 when the request expired waiting (the client
            should hit another replica / back off, not time out), 504
            when the request's own deadline passed."""
            if isinstance(e, batching_engine_lib.QueueFull):
                self._reply(429, {'error': str(e)},
                            {'Retry-After': str(int(e.retry_after))})
                return True
            if isinstance(e, batching_engine_lib.QueueExpired):
                self._reply(503, {'error': str(e)},
                            {'Retry-After': str(int(e.retry_after))})
                return True
            if isinstance(e, batching_engine_lib.DeadlineExceeded):
                self._reply(504, {'error': str(e),
                                  'reason': 'deadline_exceeded'})
                return True
            return False

        def _reject_if_draining(self) -> bool:
            """503 + Retry-After for new generation work on a draining
            replica — the LB's same-role retry lands it on a sibling
            (this is what makes a drain invisible to clients)."""
            if not server.draining:
                return False
            # Consume the request body first: replying with unread
            # body bytes on the socket would desync a keep-alive
            # connection's framing for the next request.
            self._read_body()
            _M_DRAIN_REJECTED.inc()
            self._reply(503, {'error': 'replica is draining',
                              'reason': 'draining'},
                        {'Retry-After': '5'})
            return True

        def _deadline_ms(self) -> Optional[float]:
            """The request's X-SkyTPU-Deadline-Ms, else the replica's
            env default (SKYTPU_SERVE_DEFAULT_DEADLINE_MS)."""
            raw = self.headers.get(router_lib.DEADLINE_HEADER)
            if raw:
                try:
                    ms = float(raw)
                    return ms if ms > 0 else None
                except ValueError:
                    pass
            return default_deadline_ms()

        def _qos_class(self) -> str:
            """The request's X-SkyTPU-QoS-Class, clamped to a known
            class (absent -> the env default class)."""
            return qos_lib.normalize(
                self.headers.get(router_lib.QOS_CLASS_HEADER))

        def _disconnect_probe(self):
            """True once the client socket is closed.  MSG_PEEK never
            consumes pipelined bytes: data waiting reads as 'still
            connected', only a clean EOF (or a dead socket) as gone."""
            import select  # pylint: disable=import-outside-toplevel
            import socket as socket_lib  # pylint: disable=import-outside-toplevel
            sock = self.connection

            def probe() -> bool:
                try:
                    readable, _, _ = select.select([sock], [], [], 0)
                    if not readable:
                        return False
                    return sock.recv(1, socket_lib.MSG_PEEK) == b''
                except (OSError, ValueError):
                    return True
            return probe

        def _sampling(self, req: Dict[str, Any]):
            """(temperature, top_k, seed) — request fields, falling
            back to the server's CLI defaults."""
            return (float(req.get('temperature',
                                  server.default_temperature)),
                    int(req.get('top_k', server.default_top_k)),
                    int(req.get('seed', server.default_seed)))

        def _request_id(self) -> str:
            """The propagated X-SkyTPU-Request-Id, or a fresh id when
            this server is the outermost layer that saw the request."""
            return (self.headers.get(tracing.REQUEST_ID_HEADER) or
                    tracing.new_request_id())

        def _route_meta(self) -> Optional[Dict[str, Any]]:
            """Routing facts the LB forwarded; None for direct hits.
            Counting happens here so the replica's /metrics carries
            the per-role/affinity view the CLI table shows."""
            role = self.headers.get(router_lib.ROUTED_ROLE_HEADER)
            affinity = self.headers.get(router_lib.AFFINITY_HEADER)
            handoff_ms = self.headers.get(router_lib.HANDOFF_MS_HEADER)
            if not (role or affinity or handoff_ms):
                return None
            _M_ROUTED.labels(role=role or 'unknown',
                             affinity=affinity or 'none').inc()
            try:
                ms = float(handoff_ms) if handoff_ms else None
            except ValueError:
                ms = None
            return {'routed_role': role,
                    'affinity_hit': (affinity == 'hit'
                                     if affinity else None),
                    'handoff_ms': ms,
                    'attempt': _attempt_header(
                        self.headers.get(router_lib.ATTEMPT_HEADER))}

        def do_GET(self):
            path, _, query = self.path.partition('?')
            route = (path if path in http_protocol.REPLICA_PATHS
                     else logs_lib.HEALTH_ROUTE)
            self._status = 0
            # Request-scoped log context: every record emitted while
            # handling this request carries the propagated id + this
            # replica's identity.  Probe/scrape access lines log at
            # DEBUG (logs_lib.PROBE_ROUTES) so the ring isn't
            # wall-to-wall controller scrape noise.
            with logs_lib.bind(
                    request_id=self.headers.get(
                        tracing.REQUEST_ID_HEADER),
                    attempt=_attempt_header(
                        self.headers.get(router_lib.ATTEMPT_HEADER)),
                    process='replica', replica_id=server.replica_id,
                    role=server.role):
                try:
                    self._get(path, query)
                finally:
                    logs_lib.access_log(logger, 'GET', route,
                                        self._status)

        def _get(self, path, query):
            if path == http_protocol.METRICS:
                engine = server._engine  # pylint: disable=protected-access
                if engine is not None:
                    engine.stats()  # freshen the scrape-time gauges
                body = metrics_lib.expose().encode()
                self.send_response(200)
                self.send_header('Content-Type',
                                 metrics_lib.CONTENT_TYPE)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == http_protocol.SPANS:
                # Trace-segment export: this replica's leg of each
                # request's life, for cross-process assembly
                # (sky serve trace / the controller aggregator).
                self._reply(200, server.export_spans(
                    **parse_span_query(query)))
                return
            if path == http_protocol.PROFILE:
                # Continuous-profiling export: tick-phase ring +
                # recompile sentinel (sky serve profile).
                self._reply(200, server.export_profile())
                return
            if path == http_protocol.LOGS:
                # Structured log-ring export (sky serve logs): this
                # process's recent records, seq-cursor paginated.
                self._reply(200, {'records': logs_lib.get_ring().export(
                    **logs_lib.parse_log_query(query))})
                return
            payload = {'status': 'ok',
                       'model': f'{server.cfg.d_model}x'
                                f'{server.cfg.n_layers}',
                       'role': server.role,
                       'num_hosts': server.num_hosts,
                       'draining': server.draining,
                       'weight_version': server.weight_version}
            engine = server._engine  # pylint: disable=protected-access
            code = 200
            if engine is not None:  # local bind: close() may race
                stats = engine.stats()
                payload['engine'] = stats
                if 'slice' in stats:
                    # Slice replicas surface gang health top-level so
                    # the controller's probe can tell "rank died, tear
                    # down and replace" from a transient flap.
                    payload['slice'] = stats['slice']
                if stats['failed']:
                    # A dead engine must fail the readiness probe or
                    # the LB keeps routing to a black hole.
                    payload['status'] = 'engine_failed'
                    code = 503
            self._reply(code, payload)

        def _generate_text(self):
            """Text in, text out through the checkpoint's tokenizer
            (models/tokenizer.py: real tokenizer.json / .model when
            present, byte-level fallback otherwise).  With
            {"stream": true} the response is SSE {"text": delta}
            events, decoded incrementally UTF-8-safe."""
            if self._reject_if_draining():
                return
            try:
                tok = server.tokenizer
                if server.cfg.vocab_size < tok.vocab_size:
                    raise ValueError(
                        f'model vocab {server.cfg.vocab_size} < '
                        f'tokenizer vocab {tok.vocab_size}: checkpoint '
                        'and tokenizer do not match')
                req = self._read_json()
                text = req['prompt']
                if not isinstance(text, str) or not text:
                    raise ValueError('prompt must be a non-empty string')
                ids = tok.encode(text, add_bos=True)
                if not ids:
                    raise ValueError('prompt tokenized to nothing')
                rid = self._request_id()
                if req.get('stream'):
                    self._stream_text(tok, ids, req, rid)
                    return
                t0 = time.perf_counter()
                # The engine stops AT the tokenizer's EOS (freeing the
                # slot); the lock-step scan is fixed-length, so the
                # truncation below applies either way.
                temperature, top_k, seed = self._sampling(req)
                tokens = server.generate(
                    [ids], int(req.get('max_new_tokens', 64)),
                    temperature, top_k,
                    stop_token=tok.eos_ids or None, seed=seed,
                    request_id=rid,
                    route_meta=self._route_meta(),
                    deadline_ms=self._deadline_ms(),
                    qos_class=self._qos_class(),
                    disconnect_probe=self._disconnect_probe())[0]
                _maybe_journal_request('serve_request_done',
                                       request_id=rid, status='ok',
                                       tokens=len(tokens))
                stops = [i for i, t in enumerate(tokens)
                         if t in tok.eos_ids]
                if stops:
                    tokens = tokens[:stops[0]]
                self._reply(200, {
                    'completion': tok.decode(tokens),
                    'tokens': tokens,
                    'weight_version': server.weight_version,
                    'latency_ms': round(
                        (time.perf_counter() - t0) * 1e3, 1),
                }, {tracing.REQUEST_ID_HEADER: rid})
            except ClientDisconnected:
                return  # nobody is owed a reply; the slot is freed
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {'error': str(e)})
            except Exception as e:  # pylint: disable=broad-except
                if not self._reply_backpressure(e):
                    self._reply(500, {'error': f'{type(e).__name__}: {e}'})

        def _stream_text(self, tok, ids, req, rid):
            """SSE text deltas: data: {"text": "..."} per decode step
            (skipping steps buffered inside a multi-byte sequence),
            then data: [DONE].  Needs --continuous-batching."""
            from skypilot_tpu.models import decode
            from skypilot_tpu.models.tokenizer import StreamDecoder
            if server._engine is None:  # pylint: disable=protected-access
                self._reply(400, {'error': 'streaming requires '
                                           '--continuous-batching'})
                return
            temperature, top_k, seed = self._sampling(req)
            request = server._engine.submit(  # pylint: disable=protected-access
                ids, int(req.get('max_new_tokens', 64)),
                stop_token=tok.eos_ids or None,
                sampling=decode.SamplingConfig(
                    temperature=temperature, top_k=top_k, seed=seed),
                request_id=rid, route_meta=self._route_meta(),
                deadline_ms=self._deadline_ms(),
                qos_class=self._qos_class())
            self._start_sse(rid)
            decoder = StreamDecoder(tok)
            try:
                for token in request.stream(timeout=600):
                    if token in tok.eos_ids:
                        break
                    delta = decoder.push(token)
                    if delta:
                        self._sse_chunk(json.dumps({'text': delta}))
                tail = decoder.finish()
                if tail:
                    self._sse_chunk(json.dumps({'text': tail}))
                self._sse_chunk('[DONE]')
                self.wfile.write(b'0\r\n\r\n')
            except (BrokenPipeError, ConnectionResetError):
                request.cancel()
            except Exception as e:  # pylint: disable=broad-except
                request.cancel()
                try:
                    self._sse_chunk(json.dumps(
                        {'error': f'{type(e).__name__}: {e}'}))
                    self.wfile.write(b'0\r\n\r\n')
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass

        def _generate_stream(self):
            """SSE token stream: `data: {"token": N}` per token, then
            `data: [DONE]`.  Requires --continuous-batching (the engine
            produces tokens one step at a time); single prompt only.
            The LB relays these chunks unbuffered end-to-end."""
            if self._reject_if_draining():
                return
            try:
                req = self._read_json()
                prompt = req['prompt_ids']
                if (isinstance(prompt, list) and prompt and
                        isinstance(prompt[0], list)):
                    if len(prompt) != 1:
                        raise ValueError(
                            'streaming serves one prompt per request')
                    prompt = prompt[0]
                if server._engine is None:  # pylint: disable=protected-access
                    self._reply(400, {
                        'error': 'streaming requires '
                                 '--continuous-batching'})
                    return
                from skypilot_tpu.models import decode
                temperature, top_k, seed = self._sampling(req)
                rid = self._request_id()
                request = server._engine.submit(  # pylint: disable=protected-access
                    [int(t) for t in prompt],
                    int(req.get('max_new_tokens', 16)),
                    stop_token=req.get('stop_token'),
                    sampling=decode.SamplingConfig(
                        temperature=temperature, top_k=top_k,
                        seed=seed),
                    request_id=rid, route_meta=self._route_meta(),
                    deadline_ms=self._deadline_ms(),
                    qos_class=self._qos_class())
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {'error': str(e)})
                return
            except Exception as e:  # pylint: disable=broad-except
                # Stopped/failed engine (503) or a full admission
                # queue (429 + Retry-After): an HTTP error, not a
                # dropped connection.
                if not self._reply_backpressure(e):
                    self._reply(503,
                                {'error': f'{type(e).__name__}: {e}'})
                return
            self._start_sse(rid)
            try:
                for token in request.stream(timeout=600):
                    self._sse_chunk(json.dumps({'token': token}))
                self._sse_chunk('[DONE]')
                self.wfile.write(b'0\r\n\r\n')
                _maybe_journal_request('serve_request_done',
                                       request_id=rid, status='ok',
                                       tokens=len(request.tokens))
            except (BrokenPipeError, ConnectionResetError):
                # Client went away: free the slot instead of decoding
                # the rest of max_new_tokens for nobody.
                request.cancel()
            except Exception as e:  # pylint: disable=broad-except
                # Same slot-leak logic for every other failure (stalled
                # stream timeout, other socket errors): nobody is
                # reading this request anymore.
                request.cancel()
                try:
                    self._sse_chunk(json.dumps(
                        {'error': f'{type(e).__name__}: {e}'}))
                    self.wfile.write(b'0\r\n\r\n')
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass

        def _start_sse(self, rid: Optional[str] = None) -> None:
            self.send_response(200)
            self.send_header('Content-Type', 'text/event-stream')
            self.send_header('Cache-Control', 'no-cache')
            self.send_header('Transfer-Encoding', 'chunked')
            if rid is not None:
                self.send_header(tracing.REQUEST_ID_HEADER, rid)
            self.end_headers()

        def _sse_chunk(self, data: str) -> None:
            payload = f'data: {data}\n\n'.encode()
            self.wfile.write(f'{len(payload):x}\r\n'.encode() +
                             payload + b'\r\n')
            self.wfile.flush()

        def _prefill_export(self):
            """KV handoff, prefill side: prefill the prompt and return
            its full pages as a serve/handoff.py wire payload — the
            router imports it on a decode replica and then forwards the
            request there (where it lands as a prefix hit).  A request
            carrying {"wire": "binary"} (or Accept: application/
            octet-stream) gets the raw binary frame instead of
            JSON/base64."""
            engine = server._engine  # pylint: disable=protected-access
            if engine is None:
                self._reply(400, {'error': 'KV handoff requires '
                                           '--continuous-batching'})
                return
            if self._reject_if_draining():
                return
            try:
                req = self._read_json()
                prompt = req['prompt_ids']
                if (isinstance(prompt, list) and prompt and
                        isinstance(prompt[0], list)):
                    if len(prompt) != 1:
                        raise ValueError(
                            'export serves one prompt per request')
                    prompt = prompt[0]
                binary = (req.get('wire') == 'binary' or
                          handoff_lib.CONTENT_TYPE_BINARY in
                          (self.headers.get('Accept') or ''))
                t0, wall0 = time.perf_counter(), time.time()
                payload = engine.export_prefill(
                    [int(t) for t in prompt],
                    page_size=req.get('page_size'), binary=binary)
                server.record_handoff_segment(
                    'prefill_export', self._request_id(), wall0,
                    (time.perf_counter() - t0) * 1e3,
                    attempt=_attempt_header(self.headers.get(
                        router_lib.ATTEMPT_HEADER)),
                    tokens=len(prompt))
                if binary:
                    self.send_response(200)
                    self.send_header('Content-Type',
                                     handoff_lib.CONTENT_TYPE_BINARY)
                    self.send_header('Content-Length',
                                     str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                else:
                    self._reply(200, payload)
            except (handoff_lib.HandoffError, KeyError, ValueError,
                    TypeError, json.JSONDecodeError) as e:
                self._reply(400, {'error': str(e)})
            except Exception as e:  # pylint: disable=broad-except
                if not self._reply_backpressure(e):
                    self._reply(500,
                                {'error': f'{type(e).__name__}: {e}'})

        def _kv_import(self):
            """KV handoff, decode side: adopt exported pages into the
            pool + prefix cache.  Accepts the JSON/base64 payload OR
            the binary frame (Content-Type: application/octet-stream).
            429 pages_exhausted when the pool cannot hold them right
            now; 503 when the import is refused (chaos deny /
            shedding) — the router falls back to local prefill either
            way."""
            engine = server._engine  # pylint: disable=protected-access
            if engine is None:
                self._reply(400, {'error': 'KV handoff requires '
                                           '--continuous-batching'})
                return
            if self._reject_if_draining():
                # Imported pages would die with this replica anyway.
                return
            try:
                ctype = self.headers.get('Content-Type') or ''
                if handoff_lib.CONTENT_TYPE_BINARY in ctype:
                    decoded = handoff_lib.decode_binary(
                        self._read_body())
                else:
                    decoded = handoff_lib.decode_payload(
                        self._read_json())
                t0, wall0 = time.perf_counter(), time.time()
                imported, cached = engine.import_pages(
                    decoded['hashes'], decoded['page_size'],
                    decoded['k'], decoded['v'],
                    k_scale=decoded.get('k_scale'),
                    v_scale=decoded.get('v_scale'))
                server.record_handoff_segment(
                    'kv_import', self._request_id(), wall0,
                    (time.perf_counter() - t0) * 1e3,
                    attempt=_attempt_header(self.headers.get(
                        router_lib.ATTEMPT_HEADER)),
                    imported_pages=imported, cached_pages=cached)
                self._reply(200, {'imported_pages': imported,
                                  'cached_pages': cached})
            except handoff_lib.HandoffRejected as e:
                self._reply(503, {'error': str(e),
                                  'reason': 'kv_handoff_denied'})
            except (handoff_lib.HandoffError, KeyError, ValueError,
                    TypeError, json.JSONDecodeError) as e:
                self._reply(400, {'error': str(e)})
            except Exception as e:  # pylint: disable=broad-except
                if not self._reply_backpressure(e):
                    self._reply(500,
                                {'error': f'{type(e).__name__}: {e}'})

        def _drain(self):
            """Controller retirement path: flip the replica to
            draining (new generates 503 while in-flight work
            finishes) and report the occupancy the drain waits on."""
            self._reply(200, server.drain())

        def _role_budget(self):
            """Rebalance push / morph commit: swap the fractional-role
            budget in place (see ModelServer.apply_role_budget).
            Allowed while draining — a morph drains, then commits."""
            try:
                self._reply(200,
                            server.apply_role_budget(self._read_json()))
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {'error': str(e)})
            except Exception as e:  # pylint: disable=broad-except
                self._reply(500, {'error': f'{type(e).__name__}: {e}'})

        def _weights_swap(self):
            """Live checkpoint swap (see ModelServer.weights_swap).
            Allowed while draining — a fleet can pre-stage fresh
            weights on replicas it is about to re-open."""
            try:
                self._reply(200,
                            server.weights_swap(self._read_json()))
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {'error': str(e)})
            except Exception as e:  # pylint: disable=broad-except
                self._reply(500, {'error': f'{type(e).__name__}: {e}'})

        def _prefix_export(self):
            """Drain-time sibling handoff: export the hottest prefix-
            cache pages (POOL pages — no prefill runs) so a surviving
            replica inherits the pinned sessions.  Allowed while
            draining — that is the point."""
            engine = server._engine  # pylint: disable=protected-access
            if engine is None:
                self._reply(400, {'error': 'prefix export requires '
                                           '--continuous-batching'})
                return
            try:
                req = self._read_json()
                binary = (req.get('wire') == 'binary' or
                          handoff_lib.CONTENT_TYPE_BINARY in
                          (self.headers.get('Accept') or ''))
                payload = engine.export_prefix_pages(
                    max_pages=int(req.get('max_pages', 64)),
                    binary=binary)
                if binary:
                    self.send_response(200)
                    self.send_header('Content-Type',
                                     handoff_lib.CONTENT_TYPE_BINARY)
                    self.send_header('Content-Length',
                                     str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                else:
                    self._reply(200, payload)
            except (handoff_lib.HandoffError, KeyError, ValueError,
                    TypeError, json.JSONDecodeError) as e:
                self._reply(404, {'error': str(e)})
            except Exception as e:  # pylint: disable=broad-except
                if not self._reply_backpressure(e):
                    self._reply(500,
                                {'error': f'{type(e).__name__}: {e}'})

        def do_POST(self):
            path = self.path.partition('?')[0]
            route = (path if path in http_protocol.REPLICA_PATHS
                     else 'unknown')
            self._status = 0
            with logs_lib.bind(
                    request_id=self.headers.get(
                        tracing.REQUEST_ID_HEADER),
                    attempt=_attempt_header(
                        self.headers.get(router_lib.ATTEMPT_HEADER)),
                    process='replica', replica_id=server.replica_id,
                    role=server.role):
                try:
                    self._post()
                finally:
                    logs_lib.access_log(logger, 'POST', route,
                                        self._status)

        def _post(self):
            if self.path == http_protocol.GENERATE_STREAM:
                self._generate_stream()
                return
            if self.path == http_protocol.GENERATE_TEXT:
                self._generate_text()
                return
            if self.path == http_protocol.PREFILL_EXPORT:
                self._prefill_export()
                return
            if self.path == http_protocol.KV_IMPORT:
                self._kv_import()
                return
            if self.path == http_protocol.DRAIN:
                self._drain()
                return
            if self.path == http_protocol.PREFIX_EXPORT:
                self._prefix_export()
                return
            if self.path == http_protocol.ROLE_BUDGET:
                self._role_budget()
                return
            if self.path == http_protocol.WEIGHTS_SWAP:
                self._weights_swap()
                return
            if self.path != http_protocol.GENERATE:
                self._reply(404, {'error': 'unknown path'})
                return
            if self._reject_if_draining():
                return
            try:
                req = self._read_json()
                t0 = time.perf_counter()
                temperature, top_k, seed = self._sampling(req)
                rid = self._request_id()
                qos_class = self._qos_class()
                tokens = server.generate(
                    req['prompt_ids'],
                    int(req.get('max_new_tokens', 16)),
                    temperature, top_k, seed=seed, request_id=rid,
                    route_meta=self._route_meta(),
                    deadline_ms=self._deadline_ms(),
                    qos_class=qos_class,
                    disconnect_probe=self._disconnect_probe())
                if qos_class == qos_lib.BATCH:
                    _M_BATCH_ROWS.inc(len(tokens))
                _maybe_journal_request(
                    'serve_request_done', request_id=rid, status='ok',
                    tokens=sum(len(t) for t in tokens))
                self._reply(200, {
                    'tokens': tokens,
                    'weight_version': server.weight_version,
                    'latency_ms': round(
                        (time.perf_counter() - t0) * 1e3, 1),
                }, {tracing.REQUEST_ID_HEADER: rid})
            except ClientDisconnected:
                return  # nobody is owed a reply; the slots are freed
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {'error': str(e)})
            except Exception as e:  # pylint: disable=broad-except
                # Engine failures (stopped engine, tick error, result
                # timeout) must reach the client as an HTTP error —
                # and admission-control pushback as 429/503 with
                # Retry-After — not a dropped connection.
                if not self._reply_backpressure(e):
                    self._reply(500, {'error': f'{type(e).__name__}: {e}'})

    return Handler


def serve_forever(server: ModelServer, port: int = 0) -> int:
    httpd = ThreadingHTTPServer(('0.0.0.0', port),
                                _make_handler(server))
    port = httpd.server_port
    logger.info(f'model server on :{port}')
    try:
        httpd.serve_forever()
    finally:
        server.close()
    return port


def start_background(server: ModelServer, port: int = 0):
    """Tests: start the server on a daemon thread; returns (port,
    shutdown_fn)."""
    httpd = ThreadingHTTPServer(('0.0.0.0', port),
                                _make_handler(server))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def stop() -> None:
        httpd.shutdown()
        # Close the listening socket too: a stopped replica must
        # REFUSE connections (so an LB retries a sibling fast), not
        # strand them in the accept backlog.
        httpd.server_close()

    return httpd.server_port, stop


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='tiny',
                        help="Preset name, or 'auto' to read "
                             'model_config.json from --checkpoint-dir '
                             '(converted real checkpoints).')
    parser.add_argument('--port', type=int, default=8080)
    parser.add_argument('--max-len', type=int, default=512)
    parser.add_argument('--max-batch', type=int, default=8)
    parser.add_argument('--checkpoint-dir', default=None)
    parser.add_argument('--tokenizer', default=None,
                        help='Tokenizer file/dir (default: tokenizer '
                             'files next to --checkpoint-dir, else the '
                             'byte-level fallback).')
    parser.add_argument('--quantize', default=None, choices=['int8'],
                        help='Weight-only quantization: ~2x less HBM '
                             'traffic per decoded token vs bf16.')
    parser.add_argument('--continuous-batching', action='store_true',
                        help='Slot-pool scheduling: requests join a '
                             'running batch as slots free '
                             '(max_batch = slot count); pipelined '
                             'decode ticks with on-device sampling.')
    parser.add_argument('--max-queue', type=int, default=0,
                        help='Bound the admission queue: submits '
                             'beyond this many waiting requests get '
                             'HTTP 429 + Retry-After (0 = unbounded).')
    parser.add_argument('--queue-ttl', type=float, default=None,
                        help='Seconds a request may wait queued before '
                             'it expires with HTTP 503 + Retry-After.')
    parser.add_argument('--prefill-chunk', type=int, default=512,
                        help='Chunked prefill width: long prompts '
                             'prefill in chunks interleaved with '
                             'decode ticks, bounding the ITL stall an '
                             'admission imposes on running requests.')
    import os as _os
    parser.add_argument('--kv-pages', type=int,
                        default=(int(_os.environ['SKYTPU_SERVE_KV_PAGES'])
                                 if _os.environ.get(
                                     'SKYTPU_SERVE_KV_PAGES')
                                 else None),
                        help='Paged KV cache: pool of N pages with '
                             'per-slot block tables — slot count '
                             'decouples from --max-len, pool '
                             'exhaustion backpressures (429). '
                             'Default: dense per-slot cache '
                             '(env SKYTPU_SERVE_KV_PAGES).')
    parser.add_argument('--page-size', type=int,
                        default=int(_os.environ.get(
                            'SKYTPU_SERVE_PAGE_SIZE', '16')),
                        help='Tokens per KV page (--kv-pages mode; '
                             '--max-len must be a multiple; env '
                             'SKYTPU_SERVE_PAGE_SIZE).')
    parser.add_argument('--quantize-kv', action='store_true',
                        default=_os.environ.get(
                            'SKYTPU_SERVE_KV_INT8', '') == '1',
                        help='Store KV pages as int8 with per-page-'
                             'per-head scales: ~2x tokens per byte of '
                             'cache (env SKYTPU_SERVE_KV_INT8=1).')
    parser.add_argument('--spec-tokens', type=int,
                        default=int(_os.environ.get(
                            'SKYTPU_SERVE_SPEC_TOKENS', '0')),
                        help='Self-speculative decoding: propose N '
                             'draft tokens per slot from an n-gram '
                             'prompt-lookup drafter and verify them '
                             'all in one batched tick — token streams '
                             'stay byte-identical, ITL drops by the '
                             'acceptance length on repetitive text '
                             '(--kv-pages mode; 0 = off; env '
                             'SKYTPU_SERVE_SPEC_TOKENS).')
    parser.add_argument('--no-prefix-cache', action='store_true',
                        default=_os.environ.get(
                            'SKYTPU_SERVE_PREFIX_CACHE', '1') == '0',
                        help='Disable prompt prefix reuse across '
                             'requests (--kv-pages mode; env '
                             'SKYTPU_SERVE_PREFIX_CACHE=0).')
    parser.add_argument('--temperature', type=float, default=0.0,
                        help='Default sampling temperature for '
                             'requests that omit it (0 = greedy).')
    parser.add_argument('--top-k', type=int, default=0,
                        help='Default top-k filter for requests that '
                             'omit it (0 = off).')
    parser.add_argument('--seed', type=int, default=0,
                        help='Default sampling seed for requests that '
                             'omit it.')
    parser.add_argument('--tensor', type=int, default=1,
                        help='Tensor-shard the model over N local '
                             'devices (models too big for one chip); '
                             'GSPMD partitions the decode einsums.')
    parser.add_argument('--num-hosts', type=int,
                        default=int(_os.environ.get(
                            'SKYTPU_SERVE_REPLICA_NUM_HOSTS', '1')),
                        help='Serve this replica as a multi-host SLICE '
                             'of N gang-scheduled hosts: weights '
                             'tensor/fsdp-sharded over the slice mesh, '
                             'paged KV pool sharded with them, ticks '
                             'coordinated across ranks, long prompts '
                             'prefilled sequence-parallel (ring '
                             'attention).  Emulated hosts = virtual '
                             'devices; env '
                             'SKYTPU_SERVE_REPLICA_NUM_HOSTS — set by '
                             'the controller from the role pool\'s '
                             'num_hosts:.  Requires '
                             '--continuous-batching.')
    parser.add_argument('--sp-threshold', type=int,
                        default=(int(_os.environ[
                            'SKYTPU_SLICE_SP_THRESHOLD'])
                                 if _os.environ.get(
                                     'SKYTPU_SLICE_SP_THRESHOLD')
                                 else None),
                        help='Prompt tokens at which a multi-host '
                             'replica prefills sequence-parallel in '
                             'one shot instead of chunked (default '
                             '1024; env SKYTPU_SLICE_SP_THRESHOLD).')
    parser.add_argument('--slice-sequence', type=int, default=None,
                        help='Pin the sequence-axis factor of the '
                             'slice mesh (default: hosts left over '
                             'after the tensor factor).')
    parser.add_argument('--slice-tensor', type=int, default=None,
                        help='Pin the tensor-axis factor of the slice '
                             'mesh (default: the largest divisor of '
                             '--num-hosts the model shapes support).')
    parser.add_argument('--role',
                        default=_os.environ.get(
                            'SKYTPU_SERVE_REPLICA_ROLE', 'mixed'),
                        choices=list(router_lib.ROLES),
                        help='Disaggregated-serving role this replica '
                             'advertises: prefill (serves '
                             '/prefill_export for KV handoff), decode '
                             '(receives handoffs + streams tokens), or '
                             'mixed (both; the default).  Env '
                             'SKYTPU_SERVE_REPLICA_ROLE — set by the '
                             'controller per role pool.')
    parser.add_argument('--http-server', default='async',
                        choices=['async', 'threaded'],
                        help='Connection front end: one asyncio event '
                             'loop (default; N concurrent SSE streams '
                             'without a thread per connection) or the '
                             'legacy thread-per-connection server.')
    args = parser.parse_args()
    server = ModelServer(args.model, checkpoint_dir=args.checkpoint_dir,
                         max_len=args.max_len, max_batch=args.max_batch,
                         quantize=args.quantize,
                         continuous_batching=args.continuous_batching,
                         tensor=args.tensor,
                         tokenizer_path=args.tokenizer,
                         max_queue=args.max_queue,
                         queue_ttl=args.queue_ttl,
                         prefill_chunk=args.prefill_chunk,
                         default_temperature=args.temperature,
                         default_top_k=args.top_k,
                         default_seed=args.seed,
                         kv_pages=args.kv_pages,
                         page_size=args.page_size,
                         quantize_kv=args.quantize_kv,
                         prefix_caching=not args.no_prefix_cache,
                         spec_tokens=args.spec_tokens,
                         role=args.role,
                         num_hosts=args.num_hosts,
                         sp_threshold=args.sp_threshold,
                         slice_sequence=args.slice_sequence,
                         slice_tensor=args.slice_tensor)
    if args.http_server == 'async':
        from skypilot_tpu.serve import async_server  # pylint: disable=import-outside-toplevel
        async_server.serve_forever(server, args.port)
    else:
        serve_forever(server, args.port)


if __name__ == '__main__':
    main()
