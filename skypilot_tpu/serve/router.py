"""Request router: role-aware, prefix-affine replica selection.

The load balancer used to be role-blind (round-robin / least
connections over one flat pool).  Under heavy mixed traffic that
wastes both layers PR 7 built: long-prompt prefills stall in-flight
decodes on whichever replica they land on, and repeat prefixes keep
re-prefilling because nothing routes them back to the replica whose
prefix cache already holds their pages.  This module is the pure
routing brain (`serve/load_balancer.py` owns the sockets):

- **Roles.**  Replicas run as ``prefill`` / ``decode`` / ``mixed``
  pools (service_spec ``roles:``).  Generation traffic lands on the
  decode pool (mixed when no decode pool exists); prompts at or above
  ``prefill_threshold`` tokens additionally get a *handoff source* —
  the least-loaded prefill replica, which prefills the prompt and
  exports its KV pages so the decode replica never runs the long
  prefill (serve/handoff.py carries the pages).
- **Prefix affinity.**  The head of each prompt is a session/prefix
  key; repeat keys route to the replica that served them last — the
  replica whose paged prefix cache (PR 7) already pins those pages, so
  the hit skips prefill entirely.  Affinity is advisory: a dead or
  retired replica drops out of the map and the key re-pins to the
  next target (chaos `serve_replica_flap` covers this).
- **Least-loaded.**  Within the chosen pool, pick by (live in-flight
  count here, last replica-reported load, url) — the LB's own
  in-flight view reacts instantly; the controller-synced load
  (busy+queued slots from `/health`) breaks ties across LBs.

Everything is process-local and lock-protected; no I/O.
"""
from __future__ import annotations

import collections
import dataclasses
import os
import threading
from typing import Any, Dict, Hashable, List, Optional, Sequence

from skypilot_tpu.serve import http_protocol

ROLES = ('prefill', 'decode', 'mixed')
DEFAULT_ROLE = 'mixed'

# Routing metadata headers (re-exported from the canonical protocol
# module — serve/http_protocol.py — which `sky lint`'s http-contract
# pass pins as the only home for header literals).
ROUTED_ROLE_HEADER = http_protocol.ROUTED_ROLE_HEADER
AFFINITY_HEADER = http_protocol.AFFINITY_HEADER
HANDOFF_MS_HEADER = http_protocol.HANDOFF_MS_HEADER
ATTEMPT_HEADER = http_protocol.ATTEMPT_HEADER
DEADLINE_HEADER = http_protocol.DEADLINE_HEADER

# Prompt tokens (or chars/4 for text prompts) at which a request
# counts as prefill-heavy and is eligible for prefill-pool handoff.
_PREFIX_KEY_TOKENS = 64
_PREFIX_KEY_CHARS = 256


def prefill_threshold() -> int:
    return int(os.environ.get('SKYTPU_LB_PREFILL_THRESHOLD', '64'))


def prompt_key(prompt_ids: Optional[Sequence[int]] = None,
               text: Optional[str] = None) -> Optional[Hashable]:
    """Session/prefix key of a prompt: its head, verbatim.

    The head itself is the key (no lossy hash — a collision would
    silently pin unrelated sessions together); bounded so a 100k-token
    prompt keys on its first page-aligned stretch, which is exactly
    the part the prefix cache can share."""
    if prompt_ids:
        return ('ids', tuple(int(t) for t in
                             prompt_ids[:_PREFIX_KEY_TOKENS]))
    if text:
        return ('text', text[:_PREFIX_KEY_CHARS])
    return None


@dataclasses.dataclass
class ReplicaEndpoint:
    """What the router knows about one ready replica."""
    url: str
    role: str = DEFAULT_ROLE
    load: float = 0.0           # (busy + queued) / slots, last probe
    page_size: Optional[int] = None

    def __post_init__(self) -> None:
        if self.role not in ROLES:
            raise ValueError(f'Unknown replica role {self.role!r}; '
                             f'one of {ROLES}')


@dataclasses.dataclass
class RouteDecision:
    """One routing outcome: where the request goes and why."""
    url: Optional[str]                  # None = no target (503)
    role: str = DEFAULT_ROLE            # role of the chosen target
    affinity: str = 'none'              # 'hit' | 'miss' | 'none'
    key: Optional[Hashable] = None      # prompt prefix key (affinity)
    handoff_source: Optional[str] = None  # prefill replica to export from
    page_size: Optional[int] = None     # target's KV page size (if known)


class Router:
    """Role dispatch + prefix affinity + least-loaded selection."""

    def __init__(self, threshold: Optional[int] = None,
                 affinity_capacity: int = 4096) -> None:
        self.threshold = (prefill_threshold() if threshold is None
                          else int(threshold))
        self._lock = threading.Lock()
        self._endpoints: Dict[str, ReplicaEndpoint] = {}
        # prefix key -> url last served, LRU-bounded (a router serving
        # millions of sessions must not grow without bound).
        self._affinity: 'collections.OrderedDict[Hashable, str]' = (
            collections.OrderedDict())
        self._affinity_capacity = int(affinity_capacity)
        self._inflight: Dict[str, int] = {}
        self.affinity_hits = 0
        self.affinity_misses = 0

    # ------------------------------------------------------------ fleet

    def set_endpoints(self, endpoints: List[ReplicaEndpoint]) -> None:
        """Replace the ready set (controller sync)."""
        with self._lock:
            self._endpoints = {e.url: e for e in endpoints}
            self._drop_stale_affinity_locked()

    def ensure_urls(self, urls: List[str]) -> None:
        """Reconcile with a bare url list (legacy sync / tests that
        assign `ready_urls` directly): unknown urls join as 'mixed',
        known ones keep their role/load, missing ones drop out."""
        with self._lock:
            if set(urls) == set(self._endpoints):
                return
            self._endpoints = {
                url: self._endpoints.get(url, ReplicaEndpoint(url))
                for url in urls
            }
            self._drop_stale_affinity_locked()

    def _drop_stale_affinity_locked(self) -> None:
        for key in [k for k, url in self._affinity.items()
                    if url not in self._endpoints]:
            del self._affinity[key]

    def remove_endpoint(self, url: str) -> bool:
        """Drop one replica immediately (a drain/retire push from the
        controller — don't wait for the next sync): it stops receiving
        routes and its prefix-affinity pins re-home on next use.
        Returns whether the url was present."""
        with self._lock:
            present = self._endpoints.pop(url, None) is not None
            if present:
                self._drop_stale_affinity_locked()
            return present

    def endpoints(self) -> List[ReplicaEndpoint]:
        with self._lock:
            return list(self._endpoints.values())

    def roles_present(self) -> Dict[str, int]:
        with self._lock:
            counts: Dict[str, int] = {}
            for e in self._endpoints.values():
                counts[e.role] = counts.get(e.role, 0) + 1
            return counts

    # ------------------------------------------------------- load view

    def acquire(self, url: str) -> None:
        with self._lock:
            self._inflight[url] = self._inflight.get(url, 0) + 1

    def release(self, url: str) -> None:
        with self._lock:
            n = self._inflight.get(url, 0) - 1
            if n <= 0:
                self._inflight.pop(url, None)
            else:
                self._inflight[url] = n

    def _rank_locked(self, urls: List[str]) -> List[str]:
        return sorted(urls, key=lambda u: (
            self._inflight.get(u, 0),
            self._endpoints[u].load if u in self._endpoints else 0.0,
            u))

    def _pool_locked(self, role: str) -> List[str]:
        return [u for u, e in self._endpoints.items() if e.role == role]

    def _target_pool_locked(self) -> List[str]:
        """Where generation traffic goes: the decode pool, else the
        mixed pool, else whatever is ready (a prefill-only fleet must
        still serve rather than 503)."""
        for role in ('decode', 'mixed'):
            pool = self._pool_locked(role)
            if pool:
                return pool
        return list(self._endpoints)

    # ----------------------------------------------------------- route

    def route(self, key: Optional[Hashable] = None,
              prompt_len: int = 0,
              exclude: Sequence[str] = ()) -> RouteDecision:
        """Pick the target replica (and, for prefill-heavy prompts, a
        prefill-pool handoff source).  `exclude` removes replicas that
        already failed this request (same-role failover/retry)."""
        with self._lock:
            pool = [u for u in self._target_pool_locked()
                    if u not in exclude]
            if not pool:
                return RouteDecision(url=None, key=key)
            affinity = 'none'
            target: Optional[str] = None
            if key is not None:
                pinned = self._affinity.get(key)
                if pinned is not None and pinned in pool:
                    target = pinned
                    affinity = 'hit'
                    self._affinity.move_to_end(key)
                    self.affinity_hits += 1
                else:
                    affinity = 'miss'
                    self.affinity_misses += 1
            if target is None:
                target = self._rank_locked(pool)[0]
            endpoint = self._endpoints.get(target)
            role = endpoint.role if endpoint else DEFAULT_ROLE
            handoff_source = None
            if (prompt_len >= self.threshold and role != 'prefill'):
                prefill = [u for u in self._pool_locked('prefill')
                           if u not in exclude]
                if prefill:
                    handoff_source = self._rank_locked(prefill)[0]
            return RouteDecision(
                url=target, role=role, affinity=affinity, key=key,
                handoff_source=handoff_source,
                page_size=endpoint.page_size if endpoint else None)

    def alternates(self, url: str,
                   exclude: Sequence[str] = ()) -> List[str]:
        """Same-role fallbacks for a failed/backpressured target,
        best first."""
        with self._lock:
            endpoint = self._endpoints.get(url)
            role = endpoint.role if endpoint else DEFAULT_ROLE
            skip = set(exclude) | {url}
            pool = [u for u in self._pool_locked(role) if u not in skip]
            return self._rank_locked(pool)

    def record_affinity(self, key: Optional[Hashable],
                        url: str) -> None:
        """Pin a prefix key to the replica that just served it (its
        prefix cache now holds those pages)."""
        if key is None:
            return
        with self._lock:
            self._affinity[key] = url
            self._affinity.move_to_end(key)
            while len(self._affinity) > self._affinity_capacity:
                self._affinity.popitem(last=False)

    def affinity_target(self, key: Hashable) -> Optional[str]:
        with self._lock:
            return self._affinity.get(key)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'endpoints': len(self._endpoints),
                'roles': {r: len(self._pool_locked(r)) for r in ROLES},
                'affinity_entries': len(self._affinity),
                'affinity_hits': self.affinity_hits,
                'affinity_misses': self.affinity_misses,
                'prefill_threshold': self.threshold,
            }
