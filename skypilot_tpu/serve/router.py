"""Request router: role-aware, prefix-affine replica selection.

The load balancer used to be role-blind (round-robin / least
connections over one flat pool).  Under heavy mixed traffic that
wastes both layers PR 7 built: long-prompt prefills stall in-flight
decodes on whichever replica they land on, and repeat prefixes keep
re-prefilling because nothing routes them back to the replica whose
prefix cache already holds their pages.  This module is the pure
routing brain (`serve/load_balancer.py` owns the sockets):

- **Roles.**  Replicas run as ``prefill`` / ``decode`` / ``mixed``
  pools (service_spec ``roles:``).  Generation traffic lands on the
  decode pool (mixed when no decode pool exists); prompts at or above
  ``prefill_threshold`` tokens additionally get a *handoff source* —
  the least-loaded prefill replica, which prefills the prompt and
  exports its KV pages so the decode replica never runs the long
  prefill (serve/handoff.py carries the pages).
- **Prefix affinity.**  The head of each prompt is a session/prefix
  key; repeat keys route to the replica that served them last — the
  replica whose paged prefix cache (PR 7) already pins those pages, so
  the hit skips prefill entirely.  Affinity is advisory: a dead or
  retired replica drops out of the map and the key re-pins to the
  next target (chaos `serve_replica_flap` covers this).
- **Least-loaded.**  Within the chosen pool, pick by (live in-flight
  count here, last replica-reported load, url) — the LB's own
  in-flight view reacts instantly; the controller-synced load
  (busy+queued slots from `/health`) breaks ties across LBs.
- **Regions.**  Endpoints carry the region their replica was placed
  in (`optimizer.place_role_pools`); a router with a region of its own
  (``SKYTPU_LB_REGION``) prefers same-region targets and fails over
  cross-region the moment the local pool empties (chaos
  `region_loss_failover` covers the full-region case).

The brain *state* (ready set, affinity map, in-flight counts, retired
epochs) lives in `serve/brain_store.py` — one in-process store per
single router, one shared store across a router tier.  This module
keeps the selection logic and takes the store's lock around each
decision, so tier-wide route decisions stay atomic.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Hashable, List, Optional, Sequence

from skypilot_tpu.serve import brain_store as brain_store_lib
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import roles as roles_lib

# Re-exported from the canonical role module (serve/roles.py) — this
# module historically owned the names and importers keep working.
ROLES = roles_lib.ROLES
DEFAULT_ROLE = roles_lib.DEFAULT_ROLE

# Routing metadata headers (re-exported from the canonical protocol
# module — serve/http_protocol.py — which `sky lint`'s http-contract
# pass pins as the only home for header literals).
ROUTED_ROLE_HEADER = http_protocol.ROUTED_ROLE_HEADER
AFFINITY_HEADER = http_protocol.AFFINITY_HEADER
HANDOFF_MS_HEADER = http_protocol.HANDOFF_MS_HEADER
ATTEMPT_HEADER = http_protocol.ATTEMPT_HEADER
DEADLINE_HEADER = http_protocol.DEADLINE_HEADER
QOS_CLASS_HEADER = http_protocol.QOS_CLASS_HEADER

# Prompt tokens (or chars/4 for text prompts) at which a request
# counts as prefill-heavy and is eligible for prefill-pool handoff.
_PREFIX_KEY_TOKENS = 64
_PREFIX_KEY_CHARS = 256


def prefill_threshold() -> int:
    return int(os.environ.get('SKYTPU_LB_PREFILL_THRESHOLD', '64'))


def router_region() -> Optional[str]:
    """Region identity of this router instance (region-aware dispatch
    prefers same-region replicas); unset = region-blind."""
    return os.environ.get('SKYTPU_LB_REGION') or None


def prompt_key(prompt_ids: Optional[Sequence[int]] = None,
               text: Optional[str] = None) -> Optional[Hashable]:
    """Session/prefix key of a prompt: its head, verbatim.

    The head itself is the key (no lossy hash — a collision would
    silently pin unrelated sessions together); bounded so a 100k-token
    prompt keys on its first page-aligned stretch, which is exactly
    the part the prefix cache can share."""
    if prompt_ids:
        return ('ids', tuple(int(t) for t in
                             prompt_ids[:_PREFIX_KEY_TOKENS]))
    if text:
        return ('text', text[:_PREFIX_KEY_CHARS])
    return None


@dataclasses.dataclass
class ReplicaEndpoint:
    """What the router knows about one ready replica."""
    url: str
    role: str = DEFAULT_ROLE
    load: float = 0.0           # (busy + queued) / slots, last probe
    page_size: Optional[int] = None
    region: Optional[str] = None   # placement region (None = unplaced)

    def __post_init__(self) -> None:
        if self.role not in ROLES:
            raise ValueError(f'Unknown replica role {self.role!r}; '
                             f'one of {ROLES}')


@dataclasses.dataclass
class RouteDecision:
    """One routing outcome: where the request goes and why."""
    url: Optional[str]                  # None = no target (503)
    role: str = DEFAULT_ROLE            # role of the chosen target
    affinity: str = 'none'              # 'hit' | 'miss' | 'none'
    key: Optional[Hashable] = None      # prompt prefix key (affinity)
    handoff_source: Optional[str] = None  # prefill replica to export from
    page_size: Optional[int] = None     # target's KV page size (if known)
    region: Optional[str] = None        # target's region
    cross_region: bool = False          # local pool empty -> failover


class Router:
    """Role dispatch + prefix affinity + least-loaded selection."""

    def __init__(self, threshold: Optional[int] = None,
                 affinity_capacity: int = 4096,
                 store: Optional[brain_store_lib.InProcessBrainStore]
                 = None,
                 region: Optional[str] = None) -> None:
        self.threshold = (prefill_threshold() if threshold is None
                          else int(threshold))
        self.store = store if store is not None else (
            brain_store_lib.InProcessBrainStore(
                affinity_capacity=affinity_capacity))
        self.region = region if region is not None else router_region()
        self._lock = self.store.lock

    # Counters live on the shared store so the whole tier reports one
    # affinity hit rate; exposed as properties for API compat.
    @property
    def affinity_hits(self) -> int:
        return self.store.affinity_hits

    @property
    def affinity_misses(self) -> int:
        return self.store.affinity_misses

    @property
    def _endpoints(self) -> Dict[str, ReplicaEndpoint]:
        return self.store.endpoints

    @property
    def _affinity(self):
        return self.store.affinity

    @property
    def _inflight(self) -> Dict[str, int]:
        return self.store.inflight

    # ------------------------------------------------------------ fleet

    def set_endpoints(self, endpoints: List[ReplicaEndpoint]) -> None:
        """Replace the ready set (controller sync)."""
        self.store.set_endpoints({e.url: e for e in endpoints})

    def ensure_urls(self, urls: List[str]) -> None:
        """Reconcile with a bare url list (legacy sync / tests that
        assign `ready_urls` directly): unknown urls join as 'mixed',
        known ones keep their role/load, missing ones drop out."""
        with self._lock:
            if set(urls) == set(self.store.endpoints):
                return
            self.store.set_endpoints({
                url: self.store.endpoints.get(url, ReplicaEndpoint(url))
                for url in urls
            })

    def remove_endpoint(self, url: str) -> bool:
        """Drop one replica immediately (a drain/retire push from the
        controller — don't wait for the next sync): it stops receiving
        routes and its prefix-affinity pins re-home on next use.
        Returns whether the url was present."""
        with self._lock:
            present = self.store.endpoints.pop(url, None) is not None
            if present:
                self.store.drop_stale_affinity_locked()
            return present

    def endpoints(self) -> List[ReplicaEndpoint]:
        with self._lock:
            return list(self.store.endpoints.values())

    def roles_present(self) -> Dict[str, int]:
        with self._lock:
            counts: Dict[str, int] = {}
            for e in self.store.endpoints.values():
                counts[e.role] = counts.get(e.role, 0) + 1
            return counts

    # ------------------------------------------------------- load view

    def acquire(self, url: str) -> None:
        self.store.acquire(url)

    def release(self, url: str) -> None:
        self.store.release(url)

    def _rank_locked(self, urls: List[str]) -> List[str]:
        endpoints = self.store.endpoints
        inflight = self.store.inflight
        return sorted(urls, key=lambda u: (
            inflight.get(u, 0),
            endpoints[u].load if u in endpoints else 0.0,
            u))

    def _pool_locked(self, role: str) -> List[str]:
        return [u for u, e in self.store.endpoints.items()
                if e.role == role]

    def _target_pool_locked(self) -> List[str]:
        """Where generation traffic goes: the decode pool, else the
        mixed pool, else whatever is ready (a prefill-only fleet must
        still serve rather than 503)."""
        for role in ('decode', 'mixed'):
            pool = self._pool_locked(role)
            if pool:
                return pool
        return list(self.store.endpoints)

    def _prefer_region_locked(self, pool: List[str]) -> List[str]:
        """Same-region subset when this router has a region and the
        subset is non-empty; the full pool otherwise (cross-region
        failover — a lost region must degrade latency, not serve
        503s)."""
        if not self.region:
            return pool
        local = [u for u in pool
                 if (e := self.store.endpoints.get(u)) is not None
                 and e.region == self.region]
        return local or pool

    # ----------------------------------------------------------- route

    def route(self, key: Optional[Hashable] = None,
              prompt_len: int = 0,
              exclude: Sequence[str] = ()) -> RouteDecision:
        """Pick the target replica (and, for prefill-heavy prompts, a
        prefill-pool handoff source).  `exclude` removes replicas that
        already failed this request (same-role failover/retry)."""
        with self._lock:
            pool = [u for u in self._target_pool_locked()
                    if u not in exclude]
            if not pool:
                return RouteDecision(url=None, key=key)
            regional = self._prefer_region_locked(pool)
            cross_region = bool(self.region) and regional is pool and \
                any(e.region for e in self.store.endpoints.values())
            affinity = 'none'
            target: Optional[str] = None
            if key is not None:
                pinned = self.store.affinity.get(key)
                if pinned is not None and pinned in pool:
                    # An affinity pin beats region preference: the
                    # pinned replica already holds the prefix pages.
                    target = pinned
                    affinity = 'hit'
                    self.store.affinity.move_to_end(key)
                    self.store.affinity_hits += 1
                else:
                    affinity = 'miss'
                    self.store.affinity_misses += 1
            if target is None:
                target = self._rank_locked(regional)[0]
            endpoint = self.store.endpoints.get(target)
            role = endpoint.role if endpoint else DEFAULT_ROLE
            handoff_source = None
            if (prompt_len >= self.threshold and role != 'prefill'):
                prefill = [u for u in self._pool_locked('prefill')
                           if u not in exclude]
                if prefill:
                    prefill = self._prefer_region_locked(prefill)
                    handoff_source = self._rank_locked(prefill)[0]
            return RouteDecision(
                url=target, role=role, affinity=affinity, key=key,
                handoff_source=handoff_source,
                page_size=endpoint.page_size if endpoint else None,
                region=endpoint.region if endpoint else None,
                cross_region=cross_region)

    def alternates(self, url: str,
                   exclude: Sequence[str] = ()) -> List[str]:
        """Same-role fallbacks for a failed/backpressured target,
        best first (same-region ones before cross-region)."""
        with self._lock:
            endpoint = self.store.endpoints.get(url)
            role = endpoint.role if endpoint else DEFAULT_ROLE
            skip = set(exclude) | {url}
            pool = [u for u in self._pool_locked(role) if u not in skip]
            if self.region:
                local = [u for u in pool
                         if (e := self.store.endpoints.get(u))
                         is not None and e.region == self.region]
                remote = [u for u in pool if u not in set(local)]
                return self._rank_locked(local) + \
                    self._rank_locked(remote)
            return self._rank_locked(pool)

    def record_affinity(self, key: Optional[Hashable],
                        url: str) -> None:
        """Pin a prefix key to the replica that just served it (its
        prefix cache now holds those pages)."""
        if key is None:
            return
        self.store.record_affinity(key, url)

    def affinity_target(self, key: Hashable) -> Optional[str]:
        return self.store.affinity_target(key)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'endpoints': len(self.store.endpoints),
                'roles': {r: len(self._pool_locked(r)) for r in ROLES},
                'affinity_entries': len(self.store.affinity),
                'affinity_hits': self.store.affinity_hits,
                'affinity_misses': self.store.affinity_misses,
                'prefill_threshold': self.threshold,
                'region': self.region,
            }
