"""Replica manager: launch, probe, and retire replica clusters.

Parity: /root/reference/sky/serve/replica_managers.py:58-784
(SkyPilotReplicaManager — replicas are clusters launched via recursive
sky.launch; readiness probing; preemption handling).  TPU-first: a
replica is a slice-cluster, and a preempted replica is terminated before
its slot is refilled (slices fail as a unit).
"""
from __future__ import annotations

import itertools
import json
import os
import socket
import threading
import time
import typing
from typing import Dict, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import status_lib
from skypilot_tpu.chaos import faults as chaos_faults
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import qos as qos_lib
from skypilot_tpu.serve import roles as roles_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.utils import common_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.serve.service_spec import SkyServiceSpec

logger = sky_logging.init_logger(__name__)

# Controller-side fleet gauges (observability/metrics.py): replica
# counts by status and the decode-load signal the autoscaler consumes.
_M_REPLICAS = metrics_lib.gauge(
    'skytpu_serve_replicas',
    'Replicas per service by status (set each reconcile pass).',
    ('service', 'status'))
_M_REPLICA_LOAD = metrics_lib.gauge(
    'skytpu_serve_replica_load_mean',
    'Mean busy_slots/slots across ready replicas reporting engine '
    'stats (the decode-saturation autoscaler signal).', ('service',))
_M_DRAIN_SECONDS = metrics_lib.histogram(
    'skytpu_serve_drain_seconds',
    'Wall time from replica_drain_start to replica_drain_end '
    '(graceful retirements; timeouts land in the top buckets).',
    buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0))
_M_DRAINS = metrics_lib.counter(
    'skytpu_serve_drains_total',
    'Replica drains finished, by terminal reason (drained = in-flight '
    'work ran out; timeout = SKYTPU_SERVE_DRAIN_TIMEOUT_S force-kill; '
    'dead = the replica vanished mid-drain).', ('reason',))
_M_MORPHS = metrics_lib.counter(
    'skytpu_serve_role_morphs_total',
    'Live role morphs committed (scoped drain + in-place budget swap; '
    'no restart), by the role the replica morphed INTO.', ('to_role',))

ENV_REPLICA_ID = 'SKYTPU_SERVE_REPLICA_ID'
ENV_REPLICA_PORT = 'SKYTPU_SERVE_REPLICA_PORT'
ENV_REPLICA_ROLE = 'SKYTPU_SERVE_REPLICA_ROLE'
ENV_REPLICA_NUM_HOSTS = 'SKYTPU_SERVE_REPLICA_NUM_HOSTS'


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('', 0))
        return s.getsockname()[1]


# Retirement epochs: every retirement the controller side announces
# (drain nudge or sync payload) carries one.  Time-seeded so a
# restarted controller keeps issuing LARGER epochs than anything a
# router remembers — the router clears a retired entry only once a
# sync's epoch proves the controller processed that retirement, which
# is what stops a stale sync at one router from resurrecting a replica
# a sibling router just retired (ISSUE 15 epoch guard).
_retire_epochs = itertools.count(int(time.time()))
_retire_epoch_lock = threading.Lock()


def next_retire_epoch() -> int:
    with _retire_epoch_lock:
        return next(_retire_epochs)


def current_retire_epoch() -> int:
    """The newest issued epoch (what a controller sync stamps as
    `retired_epoch`: 'my view includes every retirement up to here')."""
    with _retire_epoch_lock:
        # itertools.count has no peek; issue-and-use keeps the
        # invariant (a sync's view epoch >= every prior nudge epoch).
        return next(_retire_epochs)


def _drain_timeout() -> float:
    """Hard bound on a graceful drain: past it the replica is torn
    down with whatever it still holds (in-flight work is otherwise
    bounded only by max_new_tokens)."""
    return float(os.environ.get('SKYTPU_SERVE_DRAIN_TIMEOUT_S', '120'))


def _drain_enabled() -> bool:
    return os.environ.get('SKYTPU_SERVE_GRACEFUL_DRAIN', '1') != '0'


def _drain_export_pages() -> int:
    """Prefix pages shipped to a same-role sibling when a drain
    finishes (0 disables the handoff)."""
    return int(os.environ.get('SKYTPU_SERVE_DRAIN_EXPORT_PAGES', '64'))


def _serve_journal():
    """Drain lifecycle events are control-plane rare — journaled
    unconditionally (unlike the per-request routing events, which are
    gated on a chaos site being armed)."""
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    return events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))


def _journal_drain(event: str, **fields) -> None:
    try:
        _serve_journal().append(event, **fields)
    except Exception:  # pylint: disable=broad-except
        pass  # recording must never break the control plane


class ReplicaManager:

    def __init__(self, service_name: str, spec: 'SkyServiceSpec',
                 task: 'task_lib.Task', version: int = 1) -> None:
        self.service_name = service_name
        self.spec = spec
        self.task = task
        self.version = version
        self._launch_threads: Dict[int, threading.Thread] = {}
        self._first_probe_at: Dict[int, float] = {}
        # replica_id -> busy_slots/slots from the last healthy probe
        # (decode-saturation autoscaling signal).
        self._last_load: Dict[int, float] = {}
        # replica_id -> richer probe facts: queue depth (load signal
        # includes it: queued work is future decode pressure), KV page
        # size + prefix stats (the LB's handoff/affinity inputs).
        self._last_stats: Dict[int, Dict] = {}
        self._lock = threading.Lock()

    def set_version(self, spec: 'SkyServiceSpec', task: 'task_lib.Task',
                    version: int) -> None:
        self.spec = spec
        self.task = task
        self.version = version

    # ------------------------------------------------------------- naming

    def _cluster_name(self, replica_id: int) -> str:
        return f'{self.service_name}-{replica_id}'

    def _is_local(self) -> bool:
        for resources in self.task.resources:
            if resources.cloud is not None and str(
                    resources.cloud).lower() == 'local':
                return True
        return False

    # ----------------------------------------------------------- scale up

    def scale_up(self, use_spot: Optional[bool] = None,
                 role: str = 'mixed', num_hosts: int = 1,
                 region: Optional[str] = None) -> int:
        """Launch one replica asynchronously (into `role`'s pool);
        returns its id.  num_hosts > 1 launches it as a SLICE replica:
        a gang of that many hosts serving as one unit
        (serve/slice_replica.py — the model server reads
        SKYTPU_SERVE_REPLICA_NUM_HOSTS).  region (multi-region
        placement, optimizer.place_role_pools) is recorded and rides
        the LB sync so routers can prefer same-region replicas."""
        replica_id = serve_state.allocate_replica(
            self.service_name, self.service_name,
            is_spot=bool(use_spot), version=self.version, role=role,
            num_hosts=int(num_hosts), region=region)
        cluster_name = self._cluster_name(replica_id)
        port = _free_port() if self._is_local() else self.spec.replica_port
        thread = threading.Thread(
            target=self._launch_replica,
            args=(replica_id, cluster_name, port, use_spot, role,
                  num_hosts),
            daemon=True)
        with self._lock:
            self._launch_threads[replica_id] = thread
        thread.start()
        return replica_id

    def _launch_replica(self, replica_id: int, cluster_name: str,
                        port: int, use_spot: Optional[bool],
                        role: str = 'mixed',
                        num_hosts: int = 1) -> None:
        from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        import copy  # pylint: disable=import-outside-toplevel
        task = copy.deepcopy(self.task)
        task.update_envs({
            ENV_REPLICA_ID: str(replica_id),
            ENV_REPLICA_PORT: str(port),
            # The model server's --role default: replicas of a role
            # pool advertise it without YAML changes per pool.
            ENV_REPLICA_ROLE: role,
            # Slice width: the model server brings the replica up as a
            # num_hosts gang (--num-hosts default).
            ENV_REPLICA_NUM_HOSTS: str(int(num_hosts)),
        })
        qos_config = getattr(self.spec, 'qos', None)
        if qos_config:
            # The spec's routers.qos block rides to the replica as
            # JSON: the engine scheduler reads it for class token
            # budgets / deadline defaults (serve/qos.py).
            task.update_envs({'SKYTPU_QOS_SPEC': json.dumps(qos_config)})
        if int(num_hosts) > 1 and getattr(task, 'num_nodes', 1) <= 1:
            # The replica cluster must provision the whole slice: one
            # node per host rank (the gang supervisor fans the run
            # command out to every host).
            task.num_nodes = int(num_hosts)
        if use_spot is not None:
            task.set_resources({
                r.copy(use_spot=use_spot) for r in task.resources})
        try:
            execution.launch(task, cluster_name=cluster_name,
                             stream_logs=False, detach_run=True,
                             retry_until_up=False)
            handle = backend_utils.check_cluster_available(cluster_name)
            ips = handle.external_ips() or ['127.0.0.1']
            url = f'http://{ips[0]}:{port}'
            serve_state.set_replica_status(self.service_name, replica_id,
                                           ReplicaStatus.STARTING, url=url)
            self._first_probe_at[replica_id] = time.time()
        except exceptions.SkyTpuError as e:
            logger.warning(
                f'replica {replica_id} launch failed: '
                f'{common_utils.format_exception(e)}')
            serve_state.set_replica_status(self.service_name, replica_id,
                                           ReplicaStatus.FAILED_PROVISION)

    # --------------------------------------------------------- scale down

    def scale_down(self, replica_id: int,
                   final_status: ReplicaStatus = ReplicaStatus.TERMINATED,
                   drain: bool = False, reason: str = 'scale_down'
                   ) -> None:
        """Retire a replica.  drain=True (the controller's scale-down /
        rolling-update paths) routes a READY replica through graceful
        drain first: DRAINING status, the LB stops routing to it, its
        HTTP fronts 503 new generates, and the drain monitor tears it
        down once in-flight work finishes (or the timeout fires).
        drain=False (preemption, failed probes, service teardown) is
        the immediate kill; the row is kept in a terminal state
        (history + monotonic replica ids)."""
        if drain and _drain_enabled() and \
                final_status is ReplicaStatus.TERMINATED:
            replica = self._get_replica(replica_id)
            if replica is not None:
                status = ReplicaStatus(replica['status'])
                if status is ReplicaStatus.DRAINING:
                    return  # already draining; the monitor owns it
                if status is ReplicaStatus.READY and replica['url']:
                    self.begin_drain(replica_id, reason=reason)
                    return
        from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.SHUTTING_DOWN)
        cluster_name = self._cluster_name(replica_id)
        try:
            core.down(cluster_name)
        except (exceptions.SkyTpuError, ValueError):
            pass
        serve_state.set_replica_status(self.service_name, replica_id,
                                       final_status)
        self._first_probe_at.pop(replica_id, None)
        self._last_load.pop(replica_id, None)
        self._last_stats.pop(replica_id, None)

    def _get_replica(self, replica_id: int) -> Optional[Dict]:
        for replica in serve_state.get_replicas(self.service_name):
            if replica['replica_id'] == replica_id:
                return replica
        return None

    # ------------------------------------------------------------- drain

    def begin_drain(self, replica_id: int,
                    reason: str = 'scale_down') -> None:
        """Enter graceful drain: persist DRAINING (+ drain clock),
        journal replica_drain_start, tell the replica to refuse new
        generates, and nudge the LB off it immediately (a push, so the
        drain does not wait out a full sync interval).  The drain
        monitor (`sync_draining`) finishes the job."""
        replica = self._get_replica(replica_id)
        if replica is None:
            return
        url = replica['url']
        serve_state.set_replica_draining(self.service_name, replica_id,
                                         time.time())
        inflight = self._post_drain(url)
        _journal_drain('replica_drain_start',
                       service=self.service_name,
                       replica_id=replica_id, url=url, reason=reason,
                       inflight=inflight,
                       timeout=_drain_timeout())
        logger.info(f'replica {replica_id} draining ({reason}; '
                    f'{inflight if inflight is not None else "?"} '
                    f'in flight)')
        self._nudge_lb_retire(url)

    def notify_preemption_warning(self, replica_id: int) -> None:
        """A cloud preemption notice arrived for this replica's slice:
        drain NOW so in-flight work finishes (or hands off) before the
        capacity disappears under it."""
        self.scale_down(replica_id, drain=True,
                        reason='preemption_warning')

    def _post_drain(self, url: Optional[str]) -> Optional[int]:
        """Best-effort POST /drain; returns the replica's reported
        in-flight count (None when unreachable or not a native
        replica — user containers drain by LB exclusion alone)."""
        if not url:
            return None
        try:
            resp = requests.post(url + http_protocol.DRAIN, json={},
                                 timeout=5)
            if resp.status_code == 200:
                return resp.json().get('inflight')
        except (requests.RequestException, ValueError):
            pass
        return None

    def _nudge_lb_retire(self, url: Optional[str]) -> None:
        """Push the retirement to EVERY router instance instead of
        waiting for their next controller sync
        (~SKYTPU_SERVE_SYNC_INTERVAL): each drops the url from its
        ready set and re-pins prefix affinity right away.  The nudge
        carries a retire epoch so a router that took it can't be
        talked out of it by a sibling's staler sync.  Best effort —
        the sync payload (which excludes DRAINING replicas) is the
        backstop."""
        if not url:
            return
        record = serve_state.get_service(self.service_name)
        ports = serve_state.get_router_ports(record or {})
        if not ports:
            return
        epoch = next_retire_epoch()
        for port in ports:
            try:
                requests.post(f'http://127.0.0.1:{port}'
                              f'{http_protocol.LB_RETIRE}',
                              json={'url': url, 'epoch': epoch},
                              timeout=2)
            except requests.RequestException:
                pass

    def sync_draining(self) -> None:
        """Drain monitor: one pass over DRAINING replicas.  A replica
        leaves the state when its engine runs dry (busy + queued == 0),
        when the hard timeout fires, or when it vanishes — each path
        journals replica_drain_end{reason} and tears the cluster
        down."""
        for replica in serve_state.get_replicas(self.service_name):
            if replica['status'] == ReplicaStatus.DRAINING.value:
                self._sync_draining_one(replica)

    def _sync_draining_one(self, replica: Dict) -> None:
        replica_id = replica['replica_id']
        url = replica['url']
        started = replica.get('drain_started_at') or \
            replica.get('launched_at') or time.time()
        timeout = _drain_timeout()
        inflight: Optional[int] = None
        alive = False
        if url:
            try:
                resp = requests.get(
                    url + self.spec.readiness_path,
                    timeout=self.spec.readiness_timeout_seconds)
                alive = resp.status_code in (200, 503)
                if alive:
                    try:
                        payload = resp.json()
                        engine = payload.get('engine') or {}
                        inflight = (
                            int(engine.get('busy_slots', 0) or 0) +
                            int(engine.get('queued_requests', 0) or 0))
                        if not payload.get('draining'):
                            # The /drain from begin_drain never landed
                            # (transient failure, replica restart):
                            # re-assert, or it keeps accepting work.
                            self._post_drain(url)
                    except (ValueError, TypeError):
                        inflight = 0  # alive but no engine stats
            except requests.RequestException:
                alive = False
        if not alive:
            self._finish_drain(replica, 'dead', inflight, started)
        elif inflight is not None and inflight <= 0:
            self._finish_drain(replica, 'drained', 0, started)
        elif time.time() - started > timeout:
            self._finish_drain(replica, 'timeout', inflight, started)

    def _finish_drain(self, replica: Dict, reason: str,
                      inflight: Optional[int],
                      started: float) -> None:
        replica_id = replica['replica_id']
        url = replica['url']
        if reason != 'dead':
            self._export_hot_prefixes(replica)
        duration = max(0.0, time.time() - started)
        _M_DRAIN_SECONDS.observe(duration)
        _M_DRAINS.labels(reason=reason).inc()
        _journal_drain('replica_drain_end',
                       service=self.service_name,
                       replica_id=replica_id, url=url, reason=reason,
                       inflight=inflight, timeout=_drain_timeout(),
                       duration_s=round(duration, 3))
        logger.info(f'replica {replica_id} drain finished ({reason} '
                    f'after {duration:.1f}s); terminating')
        self.scale_down(replica_id, drain=False)

    def _export_hot_prefixes(self, replica: Dict) -> None:
        """Best-effort drain-time handoff: ship the retiring replica's
        hottest prefix-cache pages to a same-role READY sibling over
        the PR 8 wire (/prefix_export -> /kv_import), so its pinned
        sessions land warm instead of re-prefilling from scratch."""
        max_pages = _drain_export_pages()
        url = replica['url']
        if max_pages <= 0 or not url:
            return
        role = roles_lib.role_of(replica)
        sibling = next(
            (r['url'] for r in serve_state.get_replicas(
                self.service_name)
             if r['status'] == ReplicaStatus.READY.value and r['url']
             and roles_lib.role_of(r) == role
             and r['replica_id'] != replica['replica_id']), None)
        if sibling is None:
            return
        from skypilot_tpu.serve import handoff as handoff_lib  # pylint: disable=import-outside-toplevel
        status = 'ok'
        pages = 0
        try:
            resp = requests.post(
                url + http_protocol.PREFIX_EXPORT,
                json={'max_pages': max_pages, 'wire': 'binary'},
                headers={'Accept': handoff_lib.CONTENT_TYPE_BINARY},
                timeout=30)
            if resp.status_code != 200:
                raise requests.RequestException(
                    f'prefix_export -> {resp.status_code}')
            imp = requests.post(
                sibling + http_protocol.KV_IMPORT, data=resp.content,
                headers={'Content-Type':
                         handoff_lib.CONTENT_TYPE_BINARY},
                timeout=30)
            if imp.status_code != 200:
                raise requests.RequestException(
                    f'kv_import -> {imp.status_code}')
            body = imp.json() if imp.content else {}
            pages = int(body.get('imported_pages', 0) or 0) + \
                int(body.get('cached_pages', 0) or 0)
        except (requests.RequestException, ValueError) as e:
            status = f'failed: {e}'
            logger.debug(f'drain prefix handoff skipped: {e}')
        _journal_drain('drain_prefix_handoff',
                       service=self.service_name,
                       replica_id=replica['replica_id'],
                       target=sibling, pages=pages, status=status)

    # -------------------------------------------------------------- morph

    def _inflight(self, url: str) -> Optional[int]:
        """Busy + queued from the replica's health payload (None when
        unreachable or the payload has no engine stats)."""
        try:
            resp = requests.get(
                url + self.spec.readiness_path,
                timeout=self.spec.readiness_timeout_seconds)
            if resp.status_code not in (200, 503):
                return None
            engine = resp.json().get('engine') or {}
            return (int(engine.get('busy_slots', 0) or 0) +
                    int(engine.get('queued_requests', 0) or 0))
        except (requests.RequestException, ValueError, TypeError):
            return None

    def morph_replica(self, replica_id: int, new_role: str,
                      budget: Optional[Dict] = None,
                      timeout_s: Optional[float] = None) -> bool:
        """Live role morph (dynamic co-location): flip a READY replica
        to `new_role` WITHOUT restart.  Sequence: journal
        role_morph_start; park the replica DRAINING in serve_state and
        epoch-nudge every router off it (no router double-routes while
        the flip is in progress — the DRAINING row also keeps it out
        of every sync payload a router could pull mid-flip); POST
        /drain so the old role's queue runs dry while in-flight work
        finishes (bounded by the drain timeout; running decodes always
        finish); ship the hottest prefix pages to a sibling still
        serving the OLD role (that pool owns the pinned sessions);
        POST /role_budget — the engine swaps its budget profile in
        place keeping warm weights + page pool, and the server
        re-opens under the new role; persist the new role and flip the
        row back to READY so the next controller sync (view epoch >=
        the nudge) re-registers the replica in its new pool.  Journals
        role_morph_end{status: ok|timeout|error}; returns True iff the
        budget commit landed ('timeout' commits too — the drain just
        never ran dry)."""
        replica = self._get_replica(replica_id)
        if replica is None or not replica.get('url'):
            return False
        if replica['status'] != ReplicaStatus.READY.value:
            return False
        url = replica['url']
        old_role = roles_lib.role_of(replica)
        new_role = roles_lib.normalize(new_role)
        if new_role == old_role:
            return False
        t0 = time.time()
        timeout = (timeout_s if timeout_s is not None
                   else _drain_timeout())
        _journal_drain('role_morph_start', service=self.service_name,
                       replica_id=replica_id, url=url,
                       from_role=old_role, to_role=new_role)
        status = 'error'
        drained_posted = False
        try:
            # Chaos site: "deny" aborts BEFORE the scoped drain — the
            # replica keeps serving under its old role and budget.
            if chaos_injector.inject(
                    'serve.role_morph', service=self.service_name,
                    replica_id=replica_id, from_role=old_role,
                    to_role=new_role) is chaos_injector.DENY:
                return False
            serve_state.set_replica_draining(self.service_name,
                                             replica_id, t0)
            self._nudge_lb_retire(url)
            self._post_drain(url)
            drained_posted = True
            deadline = t0 + timeout
            dry = False
            while time.time() < deadline:
                inflight = self._inflight(url)
                if inflight is not None and inflight <= 0:
                    dry = True
                    break
                time.sleep(0.05)
            self._export_hot_prefixes(replica)
            payload = dict(budget or {})
            payload['role'] = new_role
            payload.setdefault('version', next_retire_epoch())
            resp = requests.post(url + http_protocol.ROLE_BUDGET,
                                 json=payload, timeout=10)
            if resp.status_code != 200 or not resp.json().get(
                    'applied'):
                raise requests.RequestException(
                    f'role_budget -> {resp.status_code}')
            serve_state.set_replica_role(self.service_name,
                                         replica_id, new_role)
            serve_state.set_replica_status(self.service_name,
                                           replica_id,
                                           ReplicaStatus.READY)
            status = 'ok' if dry else 'timeout'
            _M_MORPHS.labels(to_role=new_role).inc()
            logger.info(
                f'replica {replica_id} morphed {old_role} -> '
                f'{new_role} ({status} after '
                f'{time.time() - t0:.1f}s)')
            return True
        except (requests.RequestException, ValueError) as e:
            logger.warning(
                f'role morph {old_role} -> {new_role} failed for '
                f'replica {replica_id}: {e}')
            # Re-open under the OLD role (clears the server's
            # draining flag) and un-park the row; best effort — the
            # drain monitor's timeout is the backstop if this POST
            # fails too.
            if drained_posted:
                try:
                    requests.post(
                        url + http_protocol.ROLE_BUDGET,
                        json={'role': old_role, 'resume': True,
                              'version': next_retire_epoch()},
                        timeout=5)
                except requests.RequestException:
                    pass
                serve_state.set_replica_status(self.service_name,
                                               replica_id,
                                               ReplicaStatus.READY)
            return False
        finally:
            _journal_drain('role_morph_end',
                           service=self.service_name,
                           replica_id=replica_id, url=url,
                           from_role=old_role, to_role=new_role,
                           status=status,
                           duration_s=round(time.time() - t0, 3))

    # -------------------------------------------------------------- probe

    def _probe_one(self, replica: Dict) -> None:
        replica_id = replica['replica_id']
        url = replica['url']
        if not url:
            return
        ready = False
        degraded_slice = False
        try:
            # Chaos site: a raise here reads as a failed probe (replica
            # flap), never as a crashed reconcile loop.
            chaos_injector.inject('serve.replica_probe',
                                  service=self.service_name,
                                  replica_id=replica_id)
            resp = requests.get(url + self.spec.readiness_path,
                                timeout=self.spec.readiness_timeout_seconds)
            ready = resp.status_code == 200
            if not ready:
                # A multi-host slice replica that lost a rank reports
                # slice.degraded on its 503 health payload — that is
                # NOT a transient flap: the gang cannot re-form
                # without a rebuild, so waiting out initial_delay just
                # burns capacity.  Retire it now; the pool refills on
                # the next reconcile.
                try:
                    payload = resp.json()
                    degraded_slice = bool(
                        (payload.get('slice') or {}).get('degraded'))
                except (ValueError, TypeError):
                    pass
            # Decode-saturation signal: the native model server's
            # health payload carries engine stats; remember
            # busy_slots/slots per replica so the controller can feed
            # the autoscaler a load signal (user containers without
            # engine stats just never report).
            if ready:
                try:
                    payload = resp.json()
                    engine = payload.get('engine') or {}
                    slots = engine.get('slots')
                    if slots:
                        # Load = decode saturation PLUS queued backlog
                        # (queued work is decode pressure the busy
                        # count hasn't absorbed yet), capped at 1 so
                        # the autoscaler math stays a fraction.
                        queued = engine.get('queued_requests', 0) or 0
                        self._last_load[replica_id] = min(
                            1.0,
                            (engine.get('busy_slots', 0) + queued) /
                            slots)
                        self._last_stats[replica_id] = {
                            'queue_depth': queued,
                            'page_size': engine.get('page_size'),
                            'prefix_cache_entries': engine.get(
                                'prefix_cache_entries'),
                            # Median admission wait (seconds) from the
                            # engine's queue-wait histogram: the LB's
                            # QoS shed path stamps Retry-After from it
                            # so batch backoff tracks real congestion.
                            'queue_wait_p50': qos_lib.queue_wait_p50(
                                engine.get('queue_wait_hist')),
                        }
                except (ValueError, TypeError, ZeroDivisionError):
                    pass
        except (requests.RequestException, chaos_faults.ChaosError):
            ready = False
        status = ReplicaStatus(replica['status'])
        if ready:
            if status is not ReplicaStatus.READY:
                serve_state.set_replica_status(
                    self.service_name, replica_id, ReplicaStatus.READY)
            return
        self._last_load.pop(replica_id, None)
        self._last_stats.pop(replica_id, None)
        if degraded_slice:
            # One dead rank = the whole slice replica is done: surface
            # the NOT_READY transition for the journal/staties, then
            # tear it down and let the autoscaler replace it.  The LB
            # keeps serving off the surviving replicas meanwhile
            # (chaos scenario `replica_rank_death`).
            logger.warning(
                f'replica {replica_id} is a degraded slice (dead '
                f'rank); retiring and replacing')
            if status is ReplicaStatus.READY:
                serve_state.set_replica_status(
                    self.service_name, replica_id,
                    ReplicaStatus.NOT_READY)
            self.scale_down(replica_id,
                            final_status=ReplicaStatus.FAILED_PROBING)
            return
        if status is ReplicaStatus.READY:
            serve_state.set_replica_status(self.service_name, replica_id,
                                           ReplicaStatus.NOT_READY)
        elif status is ReplicaStatus.STARTING:
            # Anchor on the persisted launch time so the timeout
            # survives controller restarts (the in-memory map alone
            # would reset the clock and never retire a dead replica).
            first = self._first_probe_at.get(
                replica_id, replica.get('launched_at') or time.time())
            if time.time() - first > self.spec.initial_delay_seconds:
                logger.warning(f'replica {replica_id} never became ready '
                               f'within initial_delay; retiring')
                serve_state.set_replica_status(
                    self.service_name, replica_id,
                    ReplicaStatus.FAILED_INITIAL_DELAY)

    def _check_preempted(self, replica: Dict) -> bool:
        """True if the replica's cluster is gone/stopped (eviction)."""
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        try:
            record = backend_utils.refresh_cluster_record(
                replica['cluster_name'])
        except exceptions.SkyTpuError:
            return False
        return (record is None or
                record['status'] is not status_lib.ClusterStatus.UP)

    def sync(self) -> None:
        """One reconciliation pass: probe health, detect preemption,
        retire failed replicas."""
        self._export_gauges()
        for replica in serve_state.get_replicas(self.service_name):
            status = ReplicaStatus(replica['status'])
            replica_id = replica['replica_id']
            if status is ReplicaStatus.DRAINING:
                self._sync_draining_one(replica)
            elif status in (ReplicaStatus.READY, ReplicaStatus.NOT_READY,
                            ReplicaStatus.STARTING):
                if status is not ReplicaStatus.STARTING and \
                        self._check_preempted(replica):
                    logger.info(f'replica {replica_id} preempted')
                    self.scale_down(replica_id,
                                    final_status=ReplicaStatus.PREEMPTED)
                    continue
                self._probe_one(replica)
            elif status.is_terminal() and \
                    status is not ReplicaStatus.TERMINATED:
                # Newly failed replica: free its slot (the terminal row
                # is kept).  Skip once its cluster is already gone —
                # that marks the failure as handled.
                from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
                if global_user_state.get_cluster_from_name(
                        replica['cluster_name']) is not None:
                    self.scale_down(replica_id, final_status=status)

    def _export_gauges(self) -> None:
        """Fleet state -> registry gauges: every status gets set (not
        just the ones present) so a drained status reads 0, not its
        last value."""
        records = serve_state.get_replicas(self.service_name)
        by_status: Dict[str, int] = {}
        for replica in records:
            by_status[replica['status']] = (
                by_status.get(replica['status'], 0) + 1)
        for status in ReplicaStatus:
            _M_REPLICAS.labels(
                service=self.service_name, status=status.value).set(
                    by_status.get(status.value, 0))
        loads = self.ready_loads()
        if loads:
            _M_REPLICA_LOAD.labels(service=self.service_name).set(
                sum(loads) / len(loads))
        else:
            _M_REPLICA_LOAD.labels(service=self.service_name).set(0.0)

    # ------------------------------------------------------------- counts

    def active_replicas(self) -> List[Dict]:
        return [r for r in serve_state.get_replicas(self.service_name)
                if not ReplicaStatus(r['status']).is_terminal()]

    def ready_urls(self) -> List[str]:
        return [r['url'] for r in serve_state.get_replicas(
            self.service_name)
                if r['status'] == ReplicaStatus.READY.value and r['url']]

    def ready_infos(self) -> List[Dict]:
        """READY replicas with the facts the LB's router needs: url,
        role pool, last-probed load, and KV page size (handoff
        geometry).  The controller sends this through
        /controller/load_balancer_sync as `ready_replicas`."""
        infos = []
        for r in serve_state.get_replicas(self.service_name):
            if r['status'] != ReplicaStatus.READY.value or not r['url']:
                continue
            rid = r['replica_id']
            stats = self._last_stats.get(rid, {})
            infos.append({
                'url': r['url'],
                'replica_id': rid,
                'role': roles_lib.role_of(r),
                'load': self._last_load.get(rid, 0.0),
                'page_size': stats.get('page_size'),
                'queue_depth': stats.get('queue_depth', 0),
                'queue_wait_p50': stats.get('queue_wait_p50'),
                'num_hosts': r.get('num_hosts') or 1,
                'region': r.get('region'),
            })
        return infos

    def ready_loads(self, role: Optional[str] = None) -> List[float]:
        """Per-replica decode load ((busy + queued)/slots) from the
        latest healthy probes — the autoscaler's decode-saturation
        input, filterable per role pool.  Only replicas whose health
        payload reports engine stats appear."""
        ready_ids = {r['replica_id'] for r in serve_state.get_replicas(
            self.service_name)
            if r['status'] == ReplicaStatus.READY.value and
            (role is None or roles_lib.role_of(r) == role)}
        return [load for rid, load in self._last_load.items()
                if rid in ready_ids]

    def terminate_all(self) -> None:
        for replica in serve_state.get_replicas(self.service_name):
            self.scale_down(replica['replica_id'])
