"""Service spec: the `service:` section of a task YAML.

Parity: /root/reference/sky/serve/service_spec.py:312 (SkyServiceSpec —
readiness probe, replica policy, QPS target, spot fallback mix).

Disaggregated serving (`roles:`): replicas can run in independently
sized prefill / decode / mixed pools, each with its own replica bounds
and autoscaling targets — a prefill burst grows the prefill pool
without churning decode replicas (and vice versa):

    service:
      roles:
        prefill: {min_replicas: 1, max_replicas: 4,
                  target_slot_utilization: 0.8}
        decode:  {min_replicas: 2, max_replicas: 8,
                  target_qps_per_replica: 10}

Without `roles:` the service is one `mixed` pool driven by the legacy
top-level fields — nothing changes for existing YAMLs.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.utils import common_utils

DEFAULT_INITIAL_DELAY_SECONDS = 1200
DEFAULT_READINESS_PATH = '/'

VALID_ROLES = ('prefill', 'decode', 'mixed')


@dataclasses.dataclass
class RolePool:
    """Replica bounds + autoscaling targets of ONE role pool.  Carries
    the same attribute names RequestRateAutoscaler reads off the spec,
    so a pool drops in wherever a spec did."""
    role: str
    min_replicas: int = 1
    max_replicas: int = 1
    target_qps_per_replica: Optional[float] = None
    target_slot_utilization: Optional[float] = None
    upscale_delay_seconds: int = 300
    downscale_delay_seconds: int = 1200
    base_ondemand_fallback_replicas: int = 0
    # Multi-host slice replicas: every replica of this pool is a gang
    # of num_hosts hosts (serve/slice_replica.py) — weights sharded
    # over the slice mesh, one HTTP front on rank 0, replica fails and
    # is replaced as a unit.
    num_hosts: int = 1

    def __post_init__(self) -> None:
        if self.role not in VALID_ROLES:
            raise exceptions.InvalidTaskError(
                f'Unknown replica role {self.role!r}; one of '
                f'{VALID_ROLES}')
        if self.num_hosts < 1:
            raise exceptions.InvalidTaskError(
                f'{self.role}: num_hosts must be >= 1')
        if self.min_replicas < 0:
            raise exceptions.InvalidTaskError(
                f'{self.role}: min_replicas must be >= 0')
        if self.max_replicas < max(1, self.min_replicas):
            raise exceptions.InvalidTaskError(
                f'{self.role}: max_replicas must be >= '
                f'max(1, min_replicas)')
        if (self.target_qps_per_replica is not None and
                self.target_qps_per_replica <= 0):
            raise exceptions.InvalidTaskError(
                f'{self.role}: target_qps_per_replica must be positive')
        if (self.target_slot_utilization is not None and
                not 0.0 < self.target_slot_utilization <= 1.0):
            raise exceptions.InvalidTaskError(
                f'{self.role}: target_slot_utilization must be in '
                f'(0, 1]')

    @property
    def autoscaling_enabled(self) -> bool:
        return (self.target_qps_per_replica is not None or
                self.target_slot_utilization is not None)


class SkyServiceSpec:

    def __init__(self,
                 readiness_path: str = DEFAULT_READINESS_PATH,
                 initial_delay_seconds: int = DEFAULT_INITIAL_DELAY_SECONDS,
                 readiness_timeout_seconds: int = 15,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 target_qps_per_replica: Optional[float] = None,
                 target_slot_utilization: Optional[float] = None,
                 upscale_delay_seconds: int = 300,
                 downscale_delay_seconds: int = 1200,
                 replica_port: int = 8080,
                 base_ondemand_fallback_replicas: int = 0,
                 load_balancing_policy: Optional[str] = None,
                 update_mode: str = 'rolling',
                 roles: Optional[Dict[str, Dict[str, Any]]] = None,
                 routers: Optional[Dict[str, Any]] = None,
                 slos: Optional[Dict[str, Any]] = None) -> None:
        if not readiness_path.startswith('/'):
            raise exceptions.InvalidTaskError(
                f'readiness path must start with /, got {readiness_path!r}')
        if max_replicas is not None and max_replicas < min_replicas:
            raise exceptions.InvalidTaskError(
                'max_replicas must be >= min_replicas')
        if target_qps_per_replica is not None and target_qps_per_replica <= 0:
            raise exceptions.InvalidTaskError(
                'target_qps_per_replica must be positive')
        if (target_slot_utilization is not None and
                not 0.0 < target_slot_utilization <= 1.0):
            raise exceptions.InvalidTaskError(
                'target_slot_utilization must be in (0, 1]')
        self.readiness_path = readiness_path
        self.initial_delay_seconds = initial_delay_seconds
        self.readiness_timeout_seconds = readiness_timeout_seconds
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas if max_replicas is not None \
            else min_replicas
        self.target_qps_per_replica = target_qps_per_replica
        # Decode-saturation autoscaling: mean busy_slots/slots across
        # ready replicas (from the model server's /health engine stats)
        # above this fraction scales out — a replica can be decode-
        # bound at modest QPS when generations are long.
        self.target_slot_utilization = target_slot_utilization
        self.upscale_delay_seconds = upscale_delay_seconds
        self.downscale_delay_seconds = downscale_delay_seconds
        self.replica_port = replica_port
        self.base_ondemand_fallback_replicas = base_ondemand_fallback_replicas
        if load_balancing_policy is not None:
            from skypilot_tpu.serve import load_balancer as lb_lib  # pylint: disable=import-outside-toplevel
            if load_balancing_policy not in lb_lib.POLICIES:
                raise exceptions.InvalidTaskError(
                    f'Unknown load_balancing_policy '
                    f'{load_balancing_policy!r}; have '
                    f'{sorted(lb_lib.POLICIES)}')
        self.load_balancing_policy = load_balancing_policy
        if update_mode not in ('rolling', 'blue_green'):
            raise exceptions.InvalidTaskError(
                f'update_mode must be rolling or blue_green, '
                f'got {update_mode!r}')
        self.update_mode = update_mode
        # Service-level objectives (`slos:`), evaluated by the
        # controller multi-window/multi-burn-rate against the fleet
        # telemetry store (observability/slo.py); breaches journal
        # slo_burn_start/_end and show in `sky serve top`.
        self.slos: Optional[Dict[str, float]] = None
        if slos is not None:
            if not isinstance(slos, dict) or not slos:
                raise exceptions.InvalidTaskError(
                    'slos must map objective name -> target')
            common_utils.validate_schema_keys(
                slos, {'ttft_p99_ms', 'itl_p99_ms', 'error_rate',
                       'availability'}, 'slos')
            parsed: Dict[str, float] = {}
            for slo_key, value in slos.items():
                try:
                    parsed[str(slo_key)] = float(value)
                except (TypeError, ValueError):
                    raise exceptions.InvalidTaskError(
                        f'slos.{slo_key} must be a number, '
                        f'got {value!r}')  # pylint: disable=raise-missing-from
            for latency_key in ('ttft_p99_ms', 'itl_p99_ms'):
                if latency_key in parsed and parsed[latency_key] <= 0:
                    raise exceptions.InvalidTaskError(
                        f'slos.{latency_key} must be positive')
            for frac_key in ('error_rate',):
                if frac_key in parsed and \
                        not 0.0 < parsed[frac_key] < 1.0:
                    raise exceptions.InvalidTaskError(
                        f'slos.{frac_key} must be in (0, 1)')
            if 'availability' in parsed and \
                    not 0.0 < parsed['availability'] < 1.0:
                raise exceptions.InvalidTaskError(
                    'slos.availability must be in (0, 1)')
            self.slos = parsed
        # Front-door router tier (`routers:`): how many router
        # instances serve the front door, and the QoS class policy
        # they (and the engine scheduler, via SKYTPU_QOS_SPEC) enforce.
        # Reconciled by the controller like a role pool.
        self.router_replicas = 1
        self.qos: Optional[Dict[str, Any]] = None
        if routers is not None:
            if not isinstance(routers, dict):
                raise exceptions.InvalidTaskError(
                    'routers must be a mapping')
            common_utils.validate_schema_keys(
                routers, {'replicas', 'qos'}, 'routers')
            if routers.get('replicas') is not None:
                self.router_replicas = int(routers['replicas'])
                if self.router_replicas < 1:
                    raise exceptions.InvalidTaskError(
                        'routers.replicas must be >= 1')
            if routers.get('qos') is not None:
                from skypilot_tpu.serve import qos as qos_lib  # pylint: disable=import-outside-toplevel
                try:
                    qos_lib.validate_config(routers['qos'],
                                            'routers.qos')
                except ValueError as e:
                    raise exceptions.InvalidTaskError(str(e)) from e
                self.qos = {
                    name: dict(cfg)
                    for name, cfg in routers['qos'].items()}
        self.explicit_routers = routers is not None
        # Disaggregated role pools.  Explicit `roles:` builds one pool
        # per entry; otherwise the legacy top-level fields ARE the
        # single 'mixed' pool (so every consumer can just iterate
        # role_specs).
        self.explicit_roles = roles is not None
        # Dynamic co-location (fractional budgets + live morphing):
        # `roles: {dynamic: true, rebalance_window_s: ..,
        # morph_hysteresis: ..}` ride alongside the pool entries.  The
        # controller's rebalancer recomputes per-replica budget splits
        # from the aggregator's windowed per-role signals every
        # rebalance_window_s, and morphs a replica's role outright
        # when the demand imbalance exceeds the hysteresis band.
        self.dynamic_roles = False
        self.rebalance_window_s = 60.0
        self.morph_hysteresis = 0.25
        if roles:
            if not isinstance(roles, dict) or not roles:
                raise exceptions.InvalidTaskError(
                    'roles must map role name -> pool config')
            roles = dict(roles)
            if 'dynamic' in roles:
                self.dynamic_roles = bool(roles.pop('dynamic'))
            if 'rebalance_window_s' in roles:
                self.rebalance_window_s = float(
                    roles.pop('rebalance_window_s'))
                if self.rebalance_window_s <= 0:
                    raise exceptions.InvalidTaskError(
                        'roles.rebalance_window_s must be > 0')
            if 'morph_hysteresis' in roles:
                self.morph_hysteresis = float(
                    roles.pop('morph_hysteresis'))
                if not 0.0 <= self.morph_hysteresis <= 1.0:
                    raise exceptions.InvalidTaskError(
                        'roles.morph_hysteresis must be in [0, 1]')
            if not roles:
                raise exceptions.InvalidTaskError(
                    'roles must name at least one pool')
            self.role_specs: Dict[str, RolePool] = {}
            for role, pool_cfg in roles.items():
                pool_cfg = dict(pool_cfg or {})
                common_utils.validate_schema_keys(
                    pool_cfg,
                    {'replicas', 'min_replicas', 'max_replicas',
                     'target_qps_per_replica',
                     'target_slot_utilization', 'num_hosts'},
                    f'roles.{role}')
                if 'replicas' in pool_cfg:
                    n = int(pool_cfg.pop('replicas'))
                    pool_cfg.setdefault('min_replicas', n)
                    pool_cfg.setdefault('max_replicas', n)
                pool_cfg.setdefault(
                    'max_replicas',
                    max(1, int(pool_cfg.get('min_replicas', 1))))
                self.role_specs[str(role)] = RolePool(
                    role=str(role),
                    min_replicas=int(pool_cfg.get('min_replicas', 1)),
                    max_replicas=int(pool_cfg['max_replicas']),
                    target_qps_per_replica=(
                        float(pool_cfg['target_qps_per_replica'])
                        if pool_cfg.get('target_qps_per_replica')
                        is not None else None),
                    target_slot_utilization=(
                        float(pool_cfg['target_slot_utilization'])
                        if pool_cfg.get('target_slot_utilization')
                        is not None else None),
                    upscale_delay_seconds=upscale_delay_seconds,
                    downscale_delay_seconds=downscale_delay_seconds,
                    num_hosts=int(pool_cfg.get('num_hosts', 1)))
            if sum(p.max_replicas for p in self.role_specs.values()) < 1:
                raise exceptions.InvalidTaskError(
                    'roles must allow at least one replica in total')
        else:
            self.role_specs = {'mixed': RolePool(
                role='mixed',
                min_replicas=self.min_replicas,
                max_replicas=self.max_replicas,
                target_qps_per_replica=self.target_qps_per_replica,
                target_slot_utilization=self.target_slot_utilization,
                upscale_delay_seconds=self.upscale_delay_seconds,
                downscale_delay_seconds=self.downscale_delay_seconds,
                base_ondemand_fallback_replicas=(
                    self.base_ondemand_fallback_replicas))}

    @property
    def autoscaling_enabled(self) -> bool:
        return any(p.autoscaling_enabled
                   for p in self.role_specs.values())

    # --------------------------------------------------------------- yaml

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'SkyServiceSpec':
        config = dict(config)
        common_utils.validate_schema_keys(
            config, {'readiness_probe', 'replica_policy', 'replicas',
                     'replica_port', 'load_balancing_policy',
                     'update_mode', 'roles', 'routers', 'slos'},
            'service')
        kwargs: Dict[str, Any] = {}
        probe = config.get('readiness_probe')
        if isinstance(probe, str):
            kwargs['readiness_path'] = probe
        elif isinstance(probe, dict):
            common_utils.validate_schema_keys(
                probe, {'path', 'initial_delay_seconds',
                        'timeout_seconds'}, 'readiness_probe')
            if 'path' in probe:
                kwargs['readiness_path'] = probe['path']
            if 'initial_delay_seconds' in probe:
                kwargs['initial_delay_seconds'] = int(
                    probe['initial_delay_seconds'])
            if 'timeout_seconds' in probe:
                kwargs['readiness_timeout_seconds'] = int(
                    probe['timeout_seconds'])
        policy = config.get('replica_policy')
        if policy is not None:
            common_utils.validate_schema_keys(
                policy, {'min_replicas', 'max_replicas',
                         'target_qps_per_replica',
                         'target_slot_utilization',
                         'upscale_delay_seconds',
                         'downscale_delay_seconds',
                         'base_ondemand_fallback_replicas'},
                'replica_policy')
            for key in ('min_replicas', 'max_replicas',
                        'upscale_delay_seconds', 'downscale_delay_seconds',
                        'base_ondemand_fallback_replicas'):
                if key in policy:
                    kwargs[key] = int(policy[key])
            if 'target_qps_per_replica' in policy:
                kwargs['target_qps_per_replica'] = float(
                    policy['target_qps_per_replica'])
            if 'target_slot_utilization' in policy:
                kwargs['target_slot_utilization'] = float(
                    policy['target_slot_utilization'])
        elif config.get('replicas') is not None:
            # Fixed-size service shorthand (parity: reference
            # service_spec 'replicas' field).
            kwargs['min_replicas'] = int(config['replicas'])
            kwargs['max_replicas'] = int(config['replicas'])
        if config.get('replica_port') is not None:
            kwargs['replica_port'] = int(config['replica_port'])
        if config.get('load_balancing_policy') is not None:
            kwargs['load_balancing_policy'] = str(
                config['load_balancing_policy'])
        if config.get('update_mode') is not None:
            kwargs['update_mode'] = str(config['update_mode'])
        if config.get('roles') is not None:
            kwargs['roles'] = config['roles']
        if config.get('routers') is not None:
            kwargs['routers'] = config['routers']
        if config.get('slos') is not None:
            kwargs['slos'] = config['slos']
        return cls(**kwargs)

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {
            'readiness_probe': {
                'path': self.readiness_path,
                'initial_delay_seconds': self.initial_delay_seconds,
                'timeout_seconds': self.readiness_timeout_seconds,
            },
            'replica_policy': {
                'min_replicas': self.min_replicas,
                'max_replicas': self.max_replicas,
            },
            'replica_port': self.replica_port,
        }
        policy = config['replica_policy']
        if self.target_qps_per_replica is not None:
            policy['target_qps_per_replica'] = self.target_qps_per_replica
            policy['upscale_delay_seconds'] = self.upscale_delay_seconds
            policy['downscale_delay_seconds'] = self.downscale_delay_seconds
        if self.target_slot_utilization is not None:
            policy['target_slot_utilization'] = (
                self.target_slot_utilization)
            policy.setdefault('upscale_delay_seconds',
                              self.upscale_delay_seconds)
            policy.setdefault('downscale_delay_seconds',
                              self.downscale_delay_seconds)
        if self.base_ondemand_fallback_replicas:
            policy['base_ondemand_fallback_replicas'] = (
                self.base_ondemand_fallback_replicas)
        if self.load_balancing_policy is not None:
            config['load_balancing_policy'] = self.load_balancing_policy
        if self.update_mode != 'rolling':
            config['update_mode'] = self.update_mode
        if self.explicit_roles:
            roles: Dict[str, Any] = {}
            for role, pool in self.role_specs.items():
                entry: Dict[str, Any] = {
                    'min_replicas': pool.min_replicas,
                    'max_replicas': pool.max_replicas,
                }
                if pool.target_qps_per_replica is not None:
                    entry['target_qps_per_replica'] = (
                        pool.target_qps_per_replica)
                if pool.target_slot_utilization is not None:
                    entry['target_slot_utilization'] = (
                        pool.target_slot_utilization)
                if pool.num_hosts != 1:
                    entry['num_hosts'] = pool.num_hosts
                roles[role] = entry
            if self.dynamic_roles:
                roles['dynamic'] = True
            if self.rebalance_window_s != 60.0:
                roles['rebalance_window_s'] = self.rebalance_window_s
            if self.morph_hysteresis != 0.25:
                roles['morph_hysteresis'] = self.morph_hysteresis
            config['roles'] = roles
        if self.explicit_routers:
            routers: Dict[str, Any] = {'replicas': self.router_replicas}
            if self.qos is not None:
                routers['qos'] = {name: dict(cfg)
                                  for name, cfg in self.qos.items()}
            config['routers'] = routers
        if self.slos is not None:
            config['slos'] = dict(self.slos)
        return config

    def __repr__(self) -> str:
        return (f'SkyServiceSpec(replicas=[{self.min_replicas}, '
                f'{self.max_replicas}], qps_target='
                f'{self.target_qps_per_replica}, '
                f'probe={self.readiness_path!r})')
