"""Paged KV-cache management for the batching engine (host side).

vLLM-style block pooling rebuilt TPU-first: the serving KV cache is a
FIXED pool of fixed-size pages (`models/decode.py` holds the device
arrays `[L, n_pages, h_kv, page_size, d]`); this module owns every
host-side decision about those pages:

- :class:`PagePool` — the allocator.  Free list + per-page reference
  counts + pin counts; page 0 is reserved as the NULL page (freed
  slots' block tables point at it, so a stale device-side write after
  a slot is recycled can only scribble on garbage no request reads).
  Exhaustion raises :class:`PagesExhausted` — the engine turns that
  into admission backpressure (HTTP 429 + Retry-After), never an
  engine crash.  The ``serve.page_pool`` chaos site lives on the
  allocation path (deny -> exhaustion, delay -> slowed admission).
- :class:`PrefixCache` — content-addressed reuse.  Every FULL page of
  a prompt's prefilled region is registered under a chain hash
  (hash of the page's tokens and every page before it), so a request
  sharing a system prompt adopts the cached pages instead of
  re-prefilling them; entries are LRU-evicted under pool pressure.
  Only full pages are shared and shared pages are never written (the
  write cursor always lands in a privately-owned page), so sessions
  that diverge MID-page simply stop matching at that page — each gets
  its own divergence page.  :meth:`PagePool.cow` is the escape hatch
  should a writer ever hold a shared page (copy, drop the shared ref).
- :class:`PagedKVManager` — what the engine talks to: plan an
  admission (prefix match + allocation + block-table row), track which
  slot owns which pages, and release them on completion/cancel/TTL so
  the pool can never leak.

Why pages: a dense per-slot cache reserves `max_len` positions per
slot, so replica concurrency is bounded by the WORST-CASE sequence
length.  Pages bound memory by the ACTUAL tokens a request can touch
(`ceil((prompt + max_new - 1) / page_size)`), decoupling slot count
from max_len — the difference between tens and thousands of sessions
per replica at fixed HBM.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib

logger = sky_logging.init_logger(__name__)

# The reserved null page: never allocated, the block-table target of
# freed/empty slots (stale device writes land here harmlessly).
NULL_PAGE = 0

# Process-global instruments (Prometheus registry -> GET /metrics).
_M_PAGES_TOTAL = metrics_lib.gauge(
    'skytpu_engine_kv_pages_total',
    'Allocatable KV pages in the page pool (excludes the null page).')
_M_PAGES_USED = metrics_lib.gauge(
    'skytpu_engine_kv_pages_used',
    'KV pages currently referenced by live slots or the prefix cache.')
_M_PAGES_PINNED = metrics_lib.gauge(
    'skytpu_engine_kv_pages_pinned',
    'KV pages pinned by the prefix cache (reusable cached prefixes).')
_M_PREFIX_HITS = metrics_lib.counter(
    'skytpu_engine_prefix_cache_hits_total',
    'Prompt pages served from the prefix cache instead of prefill.')
_M_PREFIX_MISSES = metrics_lib.counter(
    'skytpu_engine_prefix_cache_misses_total',
    'Prompt pages that had to be prefilled (no cached prefix).')


class PagesExhausted(RuntimeError):
    """The page pool cannot satisfy an allocation right now.

    The engine maps this to admission backpressure: the request stays
    queued (or the submit gets HTTP 429 + Retry-After) until pages
    free — a full pool must degrade to honest rejections, never an
    engine failure.
    """


def chunk_hashes(token_ids: Sequence[int], page_size: int) -> List[int]:
    """Chain hashes of every FULL page of `token_ids`.

    hash(page j) covers pages 0..j (the chain), so a hit at page j
    certifies the whole prefix — two prompts can only share page j if
    every earlier token matches too.
    """
    out: List[int] = []
    prev = 0
    for start in range(0, len(token_ids) - page_size + 1, page_size):
        prev = hash((prev, tuple(token_ids[start:start + page_size])))
        out.append(prev)
    return out


class PagePool:
    """Fixed pool of KV pages: free list + refcounts + pins.

    A page is USED while `ref + pin > 0`; it returns to the free list
    when both hit zero.  Slots hold refs; the prefix cache holds pins.
    Thread-safe: submit() threads probe headroom while the engine
    worker allocates/frees.
    """

    def __init__(self, n_pages: int, page_size: int,
                 journal: Optional[Any] = None) -> None:
        if n_pages < 2:
            raise ValueError(f'page pool needs >= 2 pages (one is the '
                             f'reserved null page), got {n_pages}')
        if page_size < 1:
            raise ValueError(f'page_size must be >= 1, got {page_size}')
        self.n_pages = n_pages
        self.page_size = page_size
        self._lock = threading.Lock()
        # Page NULL_PAGE is reserved; everything else starts free.
        self._free: collections.deque = collections.deque(
            range(1, n_pages))
        self._ref = [0] * n_pages
        self._pin = [0] * n_pages
        # Chaos scenarios replay this journal to prove alloc/free
        # balance; None in production (no I/O on the admission path).
        self._journal = journal
        _M_PAGES_TOTAL.set(self.capacity)
        _M_PAGES_USED.set(0)
        _M_PAGES_PINNED.set(0)

    # ------------------------------------------------------------- views

    @property
    def capacity(self) -> int:
        return self.n_pages - 1          # null page excluded

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_count(self) -> int:
        with self._lock:
            return self.capacity - len(self._free)

    @property
    def pinned_count(self) -> int:
        with self._lock:
            return sum(1 for p in self._pin if p > 0)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._ref[page]

    # --------------------------------------------------------- lifecycle

    def alloc(self, n: int) -> List[int]:
        """Allocate n fresh pages (ref=1 each); raises PagesExhausted.

        All-or-nothing: a partial admission would strand a half-built
        block table holding pages no tick will ever use.
        """
        from skypilot_tpu.chaos import injector  # pylint: disable=import-outside-toplevel
        if injector.inject('serve.page_pool', need=n,
                           free=self.free_count) is injector.DENY:
            raise PagesExhausted(
                f'chaos: page pool denied allocation of {n} page(s)')
        with self._lock:
            if n > len(self._free):
                raise PagesExhausted(
                    f'page pool exhausted: need {n} page(s), '
                    f'{len(self._free)} free of {self.capacity}')
            pages = [self._free.popleft() for _ in range(n)]
            for p in pages:
                self._ref[p] = 1
        self._record('kv_pages_alloc', pages)
        self._set_gauges()
        return pages

    def incref(self, pages: Sequence[int]) -> None:
        with self._lock:
            for p in pages:
                if self._ref[p] + self._pin[p] <= 0:
                    raise ValueError(f'incref of unallocated page {p}')
                self._ref[p] += 1

    def decref(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; pages with no refs and no pins
        return to the free list."""
        freed: List[int] = []
        with self._lock:
            for p in pages:
                if self._ref[p] <= 0:
                    raise ValueError(f'decref of page {p} with refcount '
                                     f'{self._ref[p]}')
                self._ref[p] -= 1
                if self._ref[p] == 0 and self._pin[p] == 0:
                    self._free.append(p)
                    freed.append(p)
        if freed:
            self._record('kv_pages_free', freed)
        self._set_gauges()

    def pin(self, page: int) -> None:
        """Prefix-cache hold: keeps the page resident at ref 0."""
        with self._lock:
            if self._ref[page] + self._pin[page] <= 0:
                raise ValueError(f'pin of unallocated page {page}')
            self._pin[page] += 1
        self._set_gauges()

    def unpin(self, page: int) -> None:
        freed = False
        with self._lock:
            if self._pin[page] <= 0:
                raise ValueError(f'unpin of unpinned page {page}')
            self._pin[page] -= 1
            if self._pin[page] == 0 and self._ref[page] == 0:
                self._free.append(page)
                freed = True
        if freed:
            self._record('kv_pages_free', [page])
        self._set_gauges()

    def cow(self, page: int) -> Tuple[int, bool]:
        """Copy-on-write: make `page` safe to mutate for ONE holder.

        Returns (writable_page, needs_copy).  A page with a single
        reference and no pins is already private — returned as-is.  A
        shared/pinned page gets a fresh page allocated (the caller must
        copy the device contents) and the shared reference dropped.
        """
        with self._lock:
            if self._ref[page] == 1 and self._pin[page] == 0:
                return page, False
        fresh = self.alloc(1)[0]
        self.decref([page])
        return fresh, True

    # ----------------------------------------------------------- plumbing

    def _set_gauges(self) -> None:
        with self._lock:
            used = self.capacity - len(self._free)
            pinned = sum(1 for p in self._pin if p > 0)
        _M_PAGES_USED.set(used)
        _M_PAGES_PINNED.set(pinned)

    def _record(self, event: str, pages: List[int]) -> None:
        if self._journal is None:
            return
        try:
            self._journal.append(event, pages=list(pages), n=len(pages))
        except Exception:  # pylint: disable=broad-except
            pass  # recording must never break the admission path


class PrefixCache:
    """Chain-hash -> cached page, LRU-evicted under pool pressure.

    Entries pin their page in the pool; a match increfs the page for
    the adopting slot (the entry itself stays, so a third request hits
    too).  Only FULL prompt pages are ever registered, and full pages
    are immutable once written — matched pages are read-only by
    construction.
    """

    def __init__(self, pool: PagePool) -> None:
        self._pool = pool
        # hash -> page id, in LRU order (oldest first).
        self._entries: 'collections.OrderedDict[int, int]' = (
            collections.OrderedDict())
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def contains(self, h: int) -> bool:
        """Pure membership probe (no incref, no LRU touch) — the KV
        handoff import uses it to skip pages already resident."""
        return h in self._entries

    def match(self, hashes: Sequence[int]) -> List[int]:
        """Longest chain of cached pages for these chain hashes; the
        matched pages are incref'd for the caller (one ref per page)."""
        pages: List[int] = []
        for h in hashes:
            page = self._entries.get(h)
            if page is None:
                break
            pages.append(page)
            self._entries.move_to_end(h)   # LRU touch
        if pages:
            self._pool.incref(pages)
        self.hits += len(pages)
        self.misses += len(hashes) - len(pages)
        _M_PREFIX_HITS.inc(len(pages))
        _M_PREFIX_MISSES.inc(len(hashes) - len(pages))
        return pages

    def register(self, hashes: Sequence[int],
                 pages: Sequence[int]) -> None:
        """Publish freshly prefilled full pages (hashes[i] names
        pages[i]); duplicates keep the existing entry (first writer
        wins — both copies are identical by construction)."""
        for h, page in zip(hashes, pages):
            if h in self._entries:
                self._entries.move_to_end(h)
                continue
            self._pool.pin(page)
            self._entries[h] = page

    def evict(self, n_pages: int) -> int:
        """Unpin up to n_pages LRU entries whose pages are idle (no
        slot refs — unpinning those actually frees pages); returns how
        many pages were released to the pool."""
        released = 0
        for h in list(self._entries):
            if released >= n_pages:
                break
            page = self._entries[h]
            if self._pool.refcount(page) > 0:
                continue  # a live slot still reads it; keep the entry
            del self._entries[h]
            self._pool.unpin(page)
            released += 1
        return released

    def evictable(self) -> int:
        """Pages the cache could release right now (no slot refs)."""
        return sum(1 for page in self._entries.values()
                   if self._pool.refcount(page) == 0)

    def hot_entries(self, n: int) -> List[Tuple[int, int]]:
        """The n most-recently-used (hash, page) entries.  Entries are
        independent hash->page mappings (a chain lookup walks its own
        hashes), so any subset transfers cleanly.  Drain-time handoff
        exports these to a surviving sibling so a retirement does not
        cold-start every pinned session."""
        items = list(self._entries.items())
        return items[-n:] if n > 0 else []

    def clear(self) -> None:
        for h in list(self._entries):
            page = self._entries.pop(h)
            self._pool.unpin(page)


@dataclasses.dataclass
class AdmissionPlan:
    """Everything the engine needs to land one request in pages."""
    row: List[int]            # block-table row: reused + fresh pages
    reuse_pages: List[int]    # cached pages adopted (prefix hit)
    fresh_pages: List[int]    # newly allocated pages
    n_reuse_tokens: int       # positions [0, n_reuse_tokens) are cached
    page_hashes: List[int]    # chain hashes of the prompt's full pages

    @property
    def prefix_hit_pages(self) -> int:
        return len(self.reuse_pages)


class PagedKVManager:
    """Host-side paged-KV orchestration for one engine.

    Owns the pool + prefix cache + the slot->pages ownership map; the
    engine calls `plan_admission` when a slot frees, `register_prefix`
    when the prompt's pages are fully written, and `release` on every
    completion/cancel/expiry path.
    """

    def __init__(self, n_pages: int, page_size: int, slots: int,
                 prefix_caching: bool = True,
                 journal: Optional[Any] = None) -> None:
        self.pool = PagePool(n_pages, page_size, journal=journal)
        self.page_size = page_size
        self.prefix_caching = prefix_caching
        self.prefix = PrefixCache(self.pool)
        self._slot_pages: Dict[int, List[int]] = {}
        del slots  # sized by the engine's device arrays, not here

    # ------------------------------------------------------------ sizing

    def pages_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Pages covering every position this request can touch: the
        prompt occupies [0, n) and decode writes through position
        n + max_new - 2 (the n-1/last-token trick folds the last prompt
        token into the first decode write)."""
        total_positions = max(1, prompt_len + max_new_tokens - 1)
        return -(-total_positions // self.page_size)

    def can_admit(self, n_pages: int) -> bool:
        """Could an allocation of n_pages succeed right now (counting
        prefix entries that eviction could release)?"""
        return (self.pool.free_count + self.prefix.evictable()
                >= n_pages)

    # --------------------------------------------------------- admission

    def plan_admission(self, prompt_ids: Sequence[int],
                       max_new_tokens: int, *,
                       prefix_ok: bool = True) -> AdmissionPlan:
        """Match the prompt against the prefix cache and allocate the
        fresh remainder; raises PagesExhausted (with any matched pages
        released) when the pool cannot cover it."""
        ps = self.page_size
        n = len(prompt_ids)
        total_pages = self.pages_needed(n, max_new_tokens)
        # Only pages fully inside the PREFILLED region [0, n-1) are
        # shareable (position n-1 onward is written during decode).
        hashes = (chunk_hashes(prompt_ids[:n - 1], ps)
                  if (prefix_ok and self.prefix_caching and n > 1)
                  else [])
        reuse = self.prefix.match(hashes)
        fresh_needed = total_pages - len(reuse)
        try:
            fresh = self._alloc_with_eviction(fresh_needed)
        except PagesExhausted:
            if reuse:
                self.pool.decref(reuse)
            raise
        return AdmissionPlan(row=reuse + fresh, reuse_pages=reuse,
                             fresh_pages=fresh,
                             n_reuse_tokens=len(reuse) * ps,
                             page_hashes=hashes)

    def _alloc_with_eviction(self, n: int) -> List[int]:
        if n <= 0:
            return []
        shortfall = n - self.pool.free_count
        if shortfall > 0:
            self.prefix.evict(shortfall)
        return self.pool.alloc(n)

    def alloc_pages(self, n: int) -> List[int]:
        """Allocate n pages (evicting idle prefix entries under
        pressure); raises PagesExhausted.  The KV-handoff import path
        uses this to stage incoming pages before publishing them."""
        return self._alloc_with_eviction(n)

    def import_prefix_depth(self, hashes: Sequence[int]) -> int:
        """Longest leading run of `hashes` already resident in the
        prefix cache — an import skips those pages (the chain property
        means a later hash can only be cached if every earlier one
        was; stop at the first miss)."""
        depth = 0
        for h in hashes:
            if not self.prefix.contains(h):
                break
            depth += 1
        return depth

    def commit(self, slot: int, plan: AdmissionPlan) -> None:
        """Record slot ownership (release() undoes it)."""
        self._slot_pages[slot] = list(plan.row)

    def slot_row(self, slot: int) -> Optional[List[int]]:
        """The page row a slot currently owns (None before commit) —
        what a slice replica's rank 0 broadcasts so follower ranks can
        mirror the block-table admission without re-planning."""
        pages = self._slot_pages.get(slot)
        return list(pages) if pages is not None else None

    def abandon(self, plan: AdmissionPlan) -> None:
        """Drop a plan that never reached a slot (cancelled mid-
        prefill before commit, admission error)."""
        if plan.row:
            self.pool.decref(plan.row)

    def register_prefix(self, plan: AdmissionPlan) -> None:
        """Publish the plan's freshly-written FULL pages for reuse.
        Safe to call once the prompt's pages hold final content (at
        activation: every position < n-1 has been written)."""
        if not self.prefix_caching:
            return
        full = len(plan.page_hashes)       # full pages in [0, n-1)
        r = len(plan.reuse_pages)
        if full <= r:
            return
        self.prefix.register(plan.page_hashes[r:full],
                             plan.row[r:full])

    def release(self, slot: int) -> None:
        """Free a slot's pages (completion, cancel, TTL, shutdown);
        idempotent — release of a slot with no pages is a no-op."""
        pages = self._slot_pages.pop(slot, None)
        if pages:
            self.pool.decref(pages)

    def release_all(self) -> None:
        for slot in list(self._slot_pages):
            self.release(slot)
        self.prefix.clear()

    # ------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        stats = {
            'kv_pages_total': self.pool.capacity,
            'kv_pages_used': self.pool.used_count,
            'kv_pages_free': self.pool.free_count,
            'kv_pages_pinned': self.pool.pinned_count,
            'page_size': self.page_size,
            'prefix_cache_entries': len(self.prefix),
            'prefix_cache_hits': self.prefix.hits,
            'prefix_cache_misses': self.prefix.misses,
        }
        _M_PAGES_TOTAL.set(stats['kv_pages_total'])
        _M_PAGES_USED.set(stats['kv_pages_used'])
        _M_PAGES_PINNED.set(stats['kv_pages_pinned'])
        return stats
