"""Asyncio HTTP front end for the model server replica.

Replaces the stdlib ThreadingHTTPServer front (serve/model_server.py)
on the serving path: one event loop owns every socket — N concurrent
SSE streams, health probes, and JSON requests never spawn a thread per
connection in front of the GIL'd engine.  Token delivery rides the
engine's watcher hook (batching_engine._Request.add_watcher →
loop.call_soon_threadsafe → asyncio.Queue), so a streaming response
wakes only when its request produces a token.  Blocking compute that
cannot stream (lock-step decode.generate, engine result() for the
non-stream endpoints) runs in the default executor, bounded by the
engine's own slot count.

Zero dependencies, same endpoint surface as the threaded front
(GET /, GET /metrics, POST /generate, /generate_stream,
/generate_text — all POST routes honor and echo X-SkyTPU-Request-Id);
the hand-rolled HTTP follows serve/load_balancer.py's precedent.

Parity: the reference ships no replica server (SkyPilot serves user
containers); this is the framework-native replica of SURVEY.md's
serve stack.
"""
from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import logs as logs_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing
from skypilot_tpu.serve import batching_engine as batching_engine_lib
from skypilot_tpu.serve import handoff as handoff_lib
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import model_server as model_server_lib
from skypilot_tpu.serve import qos as qos_lib
from skypilot_tpu.serve import router as router_lib

logger = sky_logging.init_logger(__name__)

_REQUEST_ID_KEY = tracing.REQUEST_ID_HEADER.lower()


def _route_meta(headers: Dict[str, str]) -> Optional[Dict[str, Any]]:
    """Routing facts the LB forwarded (lower-cased header map); None
    for direct hits.  Mirrors the threaded front's counting."""
    role = headers.get(router_lib.ROUTED_ROLE_HEADER.lower())
    affinity = headers.get(router_lib.AFFINITY_HEADER.lower())
    handoff_ms = headers.get(router_lib.HANDOFF_MS_HEADER.lower())
    if not (role or affinity or handoff_ms):
        return None
    model_server_lib._M_ROUTED.labels(  # pylint: disable=protected-access
        role=role or 'unknown', affinity=affinity or 'none').inc()
    try:
        ms = float(handoff_ms) if handoff_ms else None
    except ValueError:
        ms = None
    return {'routed_role': role,
            'affinity_hit': affinity == 'hit' if affinity else None,
            'handoff_ms': ms,
            'attempt': model_server_lib._attempt_header(  # pylint: disable=protected-access
                headers.get(router_lib.ATTEMPT_HEADER.lower()))}

_MAX_BODY = 64 * 1024 * 1024
_IDLE_TIMEOUT = 300.0


class _HttpError(Exception):

    def __init__(self, code: int, message: str,
                 headers: Optional[Dict[str, str]] = None) -> None:
        super().__init__(message)
        self.code = code
        self.headers = headers or {}


def _backpressure_error(e: Exception) -> Optional[_HttpError]:
    """Admission-control pushback as honest HTTP: 429 + Retry-After
    when the engine queue is full, 503 + Retry-After when the request
    expired queued, 504 when its own deadline passed — so the
    LB/client backs off instead of timing out."""
    if isinstance(e, batching_engine_lib.QueueFull):
        return _HttpError(429, str(e),
                          {'Retry-After': str(int(e.retry_after))})
    if isinstance(e, batching_engine_lib.QueueExpired):
        return _HttpError(503, str(e),
                          {'Retry-After': str(int(e.retry_after))})
    if isinstance(e, batching_engine_lib.DeadlineExceeded):
        return _HttpError(504, str(e))
    return None


def _deadline_ms(headers: Dict[str, str]) -> Optional[float]:
    """The request's X-SkyTPU-Deadline-Ms (lower-cased header map),
    else the replica's env default."""
    raw = headers.get(router_lib.DEADLINE_HEADER.lower())
    if raw:
        try:
            ms = float(raw)
            return ms if ms > 0 else None
        except ValueError:
            pass
    return model_server_lib.default_deadline_ms()


def _qos_class(headers: Dict[str, str]) -> str:
    """The request's X-SkyTPU-QoS-Class (lower-cased header map),
    clamped to a known class."""
    return qos_lib.normalize(
        headers.get(router_lib.QOS_CLASS_HEADER.lower()))


async def _read_request(reader: asyncio.StreamReader
                        ) -> Optional[Tuple[str, str, Dict[str, str],
                                            bytes]]:
    """(method, path, headers, body) or None on clean EOF."""
    try:
        head = await asyncio.wait_for(reader.readuntil(b'\r\n\r\n'),
                                      timeout=_IDLE_TIMEOUT)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    except asyncio.TimeoutError:
        return None
    lines = head.decode('latin-1').split('\r\n')
    try:
        method, path, _ = lines[0].split(' ', 2)
    except ValueError as e:
        raise _HttpError(400, f'bad request line: {lines[0]!r}') from e
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if ':' in line:
            k, v = line.split(':', 1)
            headers[k.strip().lower()] = v.strip()
    try:
        length = int(headers.get('content-length', 0))
    except ValueError as e:
        raise _HttpError(400, 'bad Content-Length') from e
    if length > _MAX_BODY:
        raise _HttpError(413, 'request body too large')
    if length:
        # Same idle bound as the head read: a client that sends headers
        # then stalls must not hold a task + fd forever.
        try:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          timeout=_IDLE_TIMEOUT)
        except asyncio.TimeoutError as e:
            raise _HttpError(408, 'request body timed out') from e
    else:
        body = b''
    return method, path, headers, body


def _json_response(code: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> bytes:
    body = json.dumps(payload).encode()
    reason = {200: 'OK', 400: 'Bad Request', 404: 'Not Found',
              408: 'Request Timeout', 413: 'Payload Too Large',
              429: 'Too Many Requests',
              500: 'Internal Server Error',
              503: 'Service Unavailable',
              504: 'Gateway Timeout'}.get(code, 'Error')
    extra = ''.join(f'{k}: {v}\r\n'
                    for k, v in (headers or {}).items())
    return (f'HTTP/1.1 {code} {reason}\r\n'
            f'Content-Type: application/json\r\n'
            f'Content-Length: {len(body)}\r\n'
            f'{extra}'
            f'\r\n').encode() + body


class AsyncModelServer:
    """Serves a ModelServer's model/engine from one asyncio loop."""

    def __init__(self, server: 'model_server_lib.ModelServer') -> None:
        self.server = server
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    # ------------------------------------------------------------ bridge

    def _watch(self, request) -> 'asyncio.Queue':
        """Bridge an engine request's tokens onto the event loop."""
        assert self._loop is not None
        q: 'asyncio.Queue' = asyncio.Queue()
        loop = self._loop
        request.add_watcher(
            lambda token: loop.call_soon_threadsafe(q.put_nowait, token))
        return q

    # --------------------------------------------------------- endpoints

    def _health(self) -> Tuple[int, Dict[str, Any]]:
        server = self.server
        payload: Dict[str, Any] = {
            'status': 'ok',
            'model': f'{server.cfg.d_model}x{server.cfg.n_layers}',
            'role': server.role,
            'num_hosts': server.num_hosts,
            'draining': server.draining,
            'weight_version': server.weight_version,
        }
        engine = server._engine  # pylint: disable=protected-access
        code = 200
        if engine is not None:
            stats = engine.stats()
            payload['engine'] = stats
            if 'slice' in stats:
                # Gang health top-level: the controller probe retires a
                # degraded slice (dead rank) instead of waiting it out.
                payload['slice'] = stats['slice']
            if stats['failed']:
                payload['status'] = 'engine_failed'
                code = 503
        return code, payload

    def _sampling(self, req: Dict[str, Any]):
        """(temperature, top_k, seed) — request fields, falling back to
        the server's CLI defaults."""
        server = self.server
        return (float(req.get('temperature', server.default_temperature)),
                int(req.get('top_k', server.default_top_k)),
                int(req.get('seed', server.default_seed)))

    async def _generate(self, req: Dict[str, Any], rid: str,
                        route_meta: Optional[Dict[str, Any]] = None,
                        deadline_ms: Optional[float] = None,
                        qos_class: Optional[str] = None,
                        reader: Optional[asyncio.StreamReader] = None,
                        watch_disconnect: bool = False
                        ) -> Dict[str, Any]:
        t0 = time.perf_counter()
        temperature, top_k, seed = self._sampling(req)
        handles: list = []
        loop = asyncio.get_running_loop()

        def _call():
            # Explicit rid bind: the context carries the header's id,
            # but a direct hit may have had rid generated above.
            with logs_lib.bind(request_id=rid):
                return self.server.generate(
                    req['prompt_ids'],
                    int(req.get('max_new_tokens', 16)),
                    temperature, top_k, seed=seed, request_id=rid,
                    route_meta=route_meta, deadline_ms=deadline_ms,
                    qos_class=qos_class, on_submit=handles.extend)
        # wrap_context: run_in_executor runs the callable in a bare
        # pool thread where contextvars reset — without the copied
        # context, records emitted inside generate() would lose (or
        # worse, inherit a sibling's) request id.
        gen = loop.run_in_executor(None, logs_lib.wrap_context(_call))
        if watch_disconnect and reader is not None:
            # Connection: close (the LB's routed path, one-shot
            # clients): no further request bytes are legitimate, so a
            # read completing with EOF IS the client hanging up —
            # cancel the engine slots instead of decoding to a dead
            # socket.  Data would mean a protocol violation; treat it
            # the same and let the write path surface the error.
            watchdog = asyncio.ensure_future(reader.read(1))
            done, _ = await asyncio.wait(
                {gen, watchdog}, return_when=asyncio.FIRST_COMPLETED)
            if gen not in done:
                for handle in handles:
                    handle.cancel()
                # The executor call returns promptly once the worker
                # reaps the cancelled slots; await it so nothing leaks.
                try:
                    await gen
                except Exception:  # pylint: disable=broad-except
                    pass
                raise model_server_lib.ClientDisconnected(
                    'client disconnected mid-generation')
            watchdog.cancel()
            tokens = gen.result()
        else:
            tokens = await gen
        model_server_lib._maybe_journal_request(  # pylint: disable=protected-access
            'serve_request_done', request_id=rid, status='ok',
            tokens=sum(len(t) for t in tokens))
        if qos_class == qos_lib.BATCH:
            model_server_lib._M_BATCH_ROWS.inc(len(tokens))  # pylint: disable=protected-access
        return {'tokens': tokens,
                'weight_version': self.server.weight_version,
                'latency_ms': round((time.perf_counter() - t0) * 1e3, 1)}

    def _reject_if_draining(self) -> None:
        """503 + Retry-After for new generation work on a draining
        replica — the LB's same-role retry lands it on a sibling."""
        if self.server.draining:
            model_server_lib._M_DRAIN_REJECTED.inc()  # pylint: disable=protected-access
            raise _HttpError(503, 'replica is draining',
                             {'Retry-After': '5'})

    async def _prefix_export(self, req: Dict[str, Any],
                             binary: bool = False) -> Any:
        """Drain-time sibling handoff: export the hottest prefix-cache
        POOL pages (no prefill runs); allowed while draining."""
        engine = self.server._engine  # pylint: disable=protected-access
        if engine is None:
            raise _HttpError(400, 'prefix export requires '
                                  '--continuous-batching')
        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, logs_lib.wrap_context(
                    lambda: engine.export_prefix_pages(
                        max_pages=int(req.get('max_pages', 64)),
                        binary=binary)))
        except handoff_lib.HandoffError as e:
            raise _HttpError(404, str(e)) from e

    async def _prefill_export(self, req: Dict[str, Any],
                              binary: bool = False) -> Any:
        """KV handoff, prefill side (compute runs in the executor so
        token streams on this loop keep flowing).  binary=True returns
        the raw octet-stream frame instead of the JSON payload."""
        engine = self.server._engine  # pylint: disable=protected-access
        if engine is None:
            raise _HttpError(400, 'KV handoff requires '
                                  '--continuous-batching')
        self._reject_if_draining()
        prompt = req['prompt_ids']
        if (isinstance(prompt, list) and prompt and
                isinstance(prompt[0], list)):
            if len(prompt) != 1:
                raise _HttpError(400,
                                 'export serves one prompt per request')
            prompt = prompt[0]
        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, logs_lib.wrap_context(
                    lambda: engine.export_prefill(
                        [int(t) for t in prompt],
                        page_size=req.get('page_size'),
                        binary=binary)))
        except handoff_lib.HandoffError as e:
            raise _HttpError(400, str(e)) from e

    async def _kv_import(self, decoded: Dict[str, Any]
                         ) -> Dict[str, Any]:
        """KV handoff, decode side (waits on the engine worker in the
        executor — the loop never blocks on the import).  `decoded` is
        the wire-agnostic dict from handoff.decode_payload /
        decode_binary."""
        engine = self.server._engine  # pylint: disable=protected-access
        if engine is None:
            raise _HttpError(400, 'KV handoff requires '
                                  '--continuous-batching')
        # Imported pages would die with this replica anyway.
        self._reject_if_draining()
        try:
            imported, cached = (
                await asyncio.get_running_loop().run_in_executor(
                    None, logs_lib.wrap_context(
                        lambda: engine.import_pages(
                            decoded['hashes'], decoded['page_size'],
                            decoded['k'], decoded['v'],
                            k_scale=decoded.get('k_scale'),
                            v_scale=decoded.get('v_scale')))))
        except handoff_lib.HandoffRejected as e:
            raise _HttpError(503, str(e)) from e
        except handoff_lib.HandoffError as e:
            raise _HttpError(400, str(e)) from e
        return {'imported_pages': imported, 'cached_pages': cached}

    async def _generate_text(self, req: Dict[str, Any],
                             writer: asyncio.StreamWriter,
                             rid: str,
                             route_meta: Optional[Dict[str, Any]] = None,
                             deadline_ms: Optional[float] = None,
                             qos_class: Optional[str] = None
                             ) -> None:
        self._reject_if_draining()
        server = self.server
        tok = server.tokenizer
        if server.cfg.vocab_size < tok.vocab_size:
            raise _HttpError(
                400, f'model vocab {server.cfg.vocab_size} < tokenizer '
                     f'vocab {tok.vocab_size}: checkpoint and tokenizer '
                     'do not match')
        text = req.get('prompt')
        if not isinstance(text, str) or not text:
            raise _HttpError(400, 'prompt must be a non-empty string')
        ids = tok.encode(text, add_bos=True)
        if not ids:
            raise _HttpError(400, 'prompt tokenized to nothing')
        if req.get('stream'):
            await self._stream(writer, ids, req, rid, text_mode=True,
                               route_meta=route_meta,
                               deadline_ms=deadline_ms,
                               qos_class=qos_class)
            return
        t0 = time.perf_counter()
        temperature, top_k, seed = self._sampling(req)

        def _call():
            with logs_lib.bind(request_id=rid):
                return server.generate(
                    [ids], int(req.get('max_new_tokens', 64)),
                    temperature, top_k,
                    stop_token=tok.eos_ids or None, seed=seed,
                    request_id=rid, route_meta=route_meta,
                    deadline_ms=deadline_ms, qos_class=qos_class)
        tokens = (await asyncio.get_running_loop().run_in_executor(
            None, logs_lib.wrap_context(_call)))[0]
        stops = [i for i, t in enumerate(tokens) if t in tok.eos_ids]
        if stops:
            tokens = tokens[:stops[0]]
        writer.write(_json_response(200, {
            'completion': tok.decode(tokens),
            'tokens': tokens,
            'latency_ms': round((time.perf_counter() - t0) * 1e3, 1),
        }, {tracing.REQUEST_ID_HEADER: rid}))
        await writer.drain()

    async def _stream(self, writer: asyncio.StreamWriter, ids, req,
                      rid: str, *, text_mode: bool,
                      route_meta: Optional[Dict[str, Any]] = None,
                      deadline_ms: Optional[float] = None,
                      qos_class: Optional[str] = None
                      ) -> None:
        """SSE over chunked transfer; token events or UTF-8-safe text
        deltas.  Purely event-driven: no thread parks waiting."""
        self._reject_if_draining()
        server = self.server
        engine = server._engine  # pylint: disable=protected-access
        if engine is None:
            raise _HttpError(
                400, 'streaming requires --continuous-batching')
        tok = server.tokenizer
        # Text mode stops at the tokenizer's full stop set (model EOS +
        # chat turn-end markers — instruct checkpoints end turns there).
        # Token mode keeps the request's raw stop_token (may be int 0).
        stop_ids = ((tok.eos_ids or None) if text_mode
                    else req.get('stop_token'))
        from skypilot_tpu.models import decode  # pylint: disable=import-outside-toplevel
        temperature, top_k, seed = self._sampling(req)
        try:
            request = engine.submit(
                [int(t) for t in ids],
                int(req.get('max_new_tokens', 64 if text_mode else 16)),
                stop_token=stop_ids,
                sampling=decode.SamplingConfig(
                    temperature=temperature, top_k=top_k, seed=seed),
                request_id=rid, route_meta=route_meta,
                deadline_ms=deadline_ms, qos_class=qos_class)
        except ValueError:
            raise
        except Exception as e:  # pylint: disable=broad-except
            # Full admission queue: 429 + Retry-After.  Stopped/failed
            # engine: the replica is unavailable, not the request
            # wrong — 503 like the threaded front, so LB retry logic
            # classifies it correctly.
            bp = _backpressure_error(e)
            if bp is not None:
                raise bp from e
            raise _HttpError(503, f'{type(e).__name__}: {e}') from e
        q = self._watch(request)
        writer.write(b'HTTP/1.1 200 OK\r\n'
                     b'Content-Type: text/event-stream\r\n'
                     b'Cache-Control: no-cache\r\n' +
                     f'{tracing.REQUEST_ID_HEADER}: {rid}\r\n'.encode() +
                     b'Transfer-Encoding: chunked\r\n\r\n')

        def chunk(data: str) -> bytes:
            payload = f'data: {data}\n\n'.encode()
            return (f'{len(payload):x}\r\n'.encode() + payload + b'\r\n')

        decoder = None
        if text_mode:
            from skypilot_tpu.models.tokenizer import StreamDecoder  # pylint: disable=import-outside-toplevel
            decoder = StreamDecoder(tok)
        try:
            while True:
                token = await asyncio.wait_for(q.get(), timeout=600)
                if token is None:
                    if request.error is not None:
                        raise request.error
                    break
                if text_mode:
                    if token in tok.eos_ids:
                        break
                    delta = decoder.push(token)
                    if delta:
                        writer.write(chunk(json.dumps({'text': delta})))
                else:
                    writer.write(chunk(json.dumps({'token': token})))
                await writer.drain()
            if decoder is not None:
                tail = decoder.finish()
                if tail:
                    writer.write(chunk(json.dumps({'text': tail})))
            writer.write(chunk('[DONE]') + b'0\r\n\r\n')
            await writer.drain()
        except (BrokenPipeError, ConnectionResetError):
            # Client went away: free the slot instead of decoding the
            # rest of max_new_tokens for nobody.
            request.cancel()
        except asyncio.CancelledError:
            # Task cancelled (loop shutdown): same slot-leak logic,
            # then propagate — cancellation must not be swallowed.
            request.cancel()
            raise
        except Exception as e:  # pylint: disable=broad-except
            request.cancel()
            try:
                writer.write(chunk(json.dumps(
                    {'error': f'{type(e).__name__}: {e}'})) +
                    b'0\r\n\r\n')
                await writer.drain()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass

    # ------------------------------------------------------- connection

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    parsed = await _read_request(reader)
                except _HttpError as e:
                    # Malformed request line / Content-Length / too-big
                    # body: answer like the threaded front does, then
                    # drop the connection (framing is unreliable now).
                    writer.write(_json_response(e.code,
                                                {'error': str(e)}))
                    await writer.drain()
                    break
                except (asyncio.LimitOverrunError, ValueError) as e:
                    writer.write(_json_response(
                        400, {'error': f'bad request: {e}'}))
                    await writer.drain()
                    break
                if parsed is None:
                    break
                method, path, headers, body = parsed
                path, _, query = path.partition('?')
                route = (path if path in http_protocol.REPLICA_PATHS
                         else (logs_lib.HEALTH_ROUTE
                               if method == 'GET' else 'unknown'))
                status = 200
                # Request-scoped log context for everything this task
                # awaits while serving the request (contextvars flow
                # through awaits natively; executor hops re-wrap via
                # logs_lib.wrap_context).  Entered without `with` so
                # the existing try/except chain keeps its shape; the
                # finally below closes it.
                _log_ctx = logs_lib.bind(
                    request_id=headers.get(_REQUEST_ID_KEY),
                    attempt=model_server_lib._attempt_header(  # pylint: disable=protected-access
                        headers.get(router_lib.ATTEMPT_HEADER.lower())),
                    process='replica',
                    replica_id=self.server.replica_id,
                    role=self.server.role)
                _log_ctx.__enter__()  # pylint: disable=unnecessary-dunder-call
                try:
                    if method == 'GET':
                        if path == http_protocol.METRICS:
                            engine = self.server._engine  # pylint: disable=protected-access
                            if engine is not None:
                                engine.stats()  # freshen gauges
                            text = metrics_lib.expose().encode()
                            writer.write(
                                (f'HTTP/1.1 200 OK\r\n'
                                 f'Content-Type: '
                                 f'{metrics_lib.CONTENT_TYPE}\r\n'
                                 f'Content-Length: {len(text)}\r\n'
                                 f'\r\n').encode() + text)
                        elif path == http_protocol.SPANS:
                            # Trace-segment export for cross-process
                            # assembly (sky serve trace).
                            writer.write(_json_response(
                                200, self.server.export_spans(
                                    **model_server_lib.parse_span_query(
                                        query))))
                        elif path == http_protocol.PROFILE:
                            # Continuous-profiling export (tick-phase
                            # ring + recompile sentinel).
                            writer.write(_json_response(
                                200, self.server.export_profile()))
                        elif path == http_protocol.LOGS:
                            # Structured log-ring export (sky serve
                            # logs): recent records, seq-paginated.
                            writer.write(_json_response(
                                200, {'records':
                                      logs_lib.get_ring().export(
                                          **logs_lib.parse_log_query(
                                              query))}))
                        else:
                            code, payload = self._health()
                            status = code
                            writer.write(_json_response(code, payload))
                        await writer.drain()
                        continue
                    if method != 'POST':
                        raise _HttpError(404, 'unknown method')
                    ctype = headers.get('content-type') or ''
                    if (path == http_protocol.KV_IMPORT and
                            handoff_lib.CONTENT_TYPE_BINARY in ctype):
                        # Binary handoff frame: raw array bytes, no
                        # JSON parse of a megabyte body.
                        try:
                            decoded = handoff_lib.decode_binary(body)
                        except handoff_lib.HandoffError as e:
                            raise _HttpError(400, str(e)) from e
                        t0, wall0 = time.perf_counter(), time.time()
                        result = await self._kv_import(decoded)
                        self.server.record_handoff_segment(
                            'kv_import',
                            headers.get(_REQUEST_ID_KEY) or
                            tracing.new_request_id(), wall0,
                            (time.perf_counter() - t0) * 1e3,
                            attempt=model_server_lib._attempt_header(  # pylint: disable=protected-access
                                headers.get(
                                    router_lib.ATTEMPT_HEADER.lower())),
                            imported_pages=result.get(
                                'imported_pages'),
                            cached_pages=result.get('cached_pages'))
                        writer.write(_json_response(200, result))
                        await writer.drain()
                        continue
                    try:
                        req = json.loads(body or b'{}')
                    except json.JSONDecodeError as e:
                        raise _HttpError(400, f'bad JSON: {e}') from e
                    # Propagated request id (LB injects one when the
                    # client didn't send it); echoed on every reply.
                    rid = (headers.get(_REQUEST_ID_KEY) or
                           tracing.new_request_id())
                    meta = _route_meta(headers)
                    deadline_ms = _deadline_ms(headers)
                    qos_class = _qos_class(headers)
                    if path == http_protocol.GENERATE:
                        self._reject_if_draining()
                        one_shot = 'close' in (
                            headers.get('connection') or '').lower()
                        try:
                            payload = await self._generate(
                                req, rid, meta,
                                deadline_ms=deadline_ms,
                                qos_class=qos_class,
                                reader=reader,
                                watch_disconnect=one_shot)
                        except model_server_lib.ClientDisconnected:
                            break  # no reply owed; slots already freed
                        writer.write(_json_response(
                            200, payload,
                            {tracing.REQUEST_ID_HEADER: rid}))
                        await writer.drain()
                    elif path == http_protocol.GENERATE_STREAM:
                        prompt = req['prompt_ids']
                        if (isinstance(prompt, list) and prompt and
                                isinstance(prompt[0], list)):
                            if len(prompt) != 1:
                                raise _HttpError(
                                    400,
                                    'streaming serves one prompt '
                                    'per request')
                            prompt = prompt[0]
                        await self._stream(writer, prompt, req, rid,
                                           text_mode=False,
                                           route_meta=meta,
                                           deadline_ms=deadline_ms,
                                           qos_class=qos_class)
                    elif path == http_protocol.GENERATE_TEXT:
                        await self._generate_text(
                            req, writer, rid, meta,
                            deadline_ms=deadline_ms,
                            qos_class=qos_class)
                    elif path == http_protocol.DRAIN:
                        writer.write(_json_response(
                            200, self.server.drain()))
                        await writer.drain()
                    elif path == http_protocol.ROLE_BUDGET:
                        try:
                            result = self.server.apply_role_budget(req)
                        except (KeyError, ValueError, TypeError) as e:
                            raise _HttpError(400, str(e)) from e
                        writer.write(_json_response(200, result))
                        await writer.drain()
                    elif path == http_protocol.WEIGHTS_SWAP:
                        # Checkpoint restore is blocking I/O: run it in
                        # the executor so in-flight streams keep
                        # flowing while the weights load.
                        try:
                            result = await (
                                asyncio.get_running_loop()
                                .run_in_executor(
                                    None, logs_lib.wrap_context(
                                        lambda r=req: (
                                            self.server
                                            .weights_swap(r)))))
                        except (KeyError, ValueError, TypeError) as e:
                            raise _HttpError(400, str(e)) from e
                        writer.write(_json_response(200, result))
                        await writer.drain()
                    elif path == http_protocol.PREFIX_EXPORT:
                        binary = (req.get('wire') == 'binary' or
                                  handoff_lib.CONTENT_TYPE_BINARY in
                                  (headers.get('accept') or ''))
                        result = await self._prefix_export(
                            req, binary=binary)
                        if binary:
                            writer.write(
                                (f'HTTP/1.1 200 OK\r\n'
                                 f'Content-Type: '
                                 f'{handoff_lib.CONTENT_TYPE_BINARY}'
                                 f'\r\nContent-Length: '
                                 f'{len(result)}\r\n\r\n'
                                 ).encode() + result)
                        else:
                            writer.write(_json_response(200, result))
                        await writer.drain()
                    elif path == http_protocol.PREFILL_EXPORT:
                        binary = (req.get('wire') == 'binary' or
                                  handoff_lib.CONTENT_TYPE_BINARY in
                                  (headers.get('accept') or ''))
                        t0, wall0 = time.perf_counter(), time.time()
                        result = await self._prefill_export(
                            req, binary=binary)
                        self.server.record_handoff_segment(
                            'prefill_export', rid, wall0,
                            (time.perf_counter() - t0) * 1e3,
                            attempt=model_server_lib._attempt_header(  # pylint: disable=protected-access
                                headers.get(
                                    router_lib.ATTEMPT_HEADER.lower())))
                        if binary:
                            writer.write(
                                (f'HTTP/1.1 200 OK\r\n'
                                 f'Content-Type: '
                                 f'{handoff_lib.CONTENT_TYPE_BINARY}'
                                 f'\r\nContent-Length: '
                                 f'{len(result)}\r\n\r\n'
                                 ).encode() + result)
                        else:
                            writer.write(_json_response(200, result))
                        await writer.drain()
                    elif path == http_protocol.KV_IMPORT:
                        try:
                            decoded = handoff_lib.decode_payload(req)
                        except handoff_lib.HandoffError as e:
                            raise _HttpError(400, str(e)) from e
                        t0, wall0 = time.perf_counter(), time.time()
                        result = await self._kv_import(decoded)
                        self.server.record_handoff_segment(
                            'kv_import', rid, wall0,
                            (time.perf_counter() - t0) * 1e3,
                            attempt=model_server_lib._attempt_header(  # pylint: disable=protected-access
                                headers.get(
                                    router_lib.ATTEMPT_HEADER.lower())),
                            imported_pages=result.get(
                                'imported_pages'),
                            cached_pages=result.get('cached_pages'))
                        writer.write(_json_response(200, result))
                        await writer.drain()
                    else:
                        raise _HttpError(404, 'unknown path')
                except _HttpError as e:
                    status = e.code
                    writer.write(_json_response(
                        e.code, {'error': str(e)}, e.headers))
                    await writer.drain()
                except (KeyError, ValueError, TypeError) as e:
                    status = 400
                    writer.write(_json_response(400, {'error': str(e)}))
                    await writer.drain()
                except (BrokenPipeError, ConnectionResetError):
                    status = 0  # client gone; nothing went on the wire
                    break
                except Exception as e:  # pylint: disable=broad-except
                    # Engine failures must reach the client as HTTP,
                    # not a dropped connection; admission pushback as
                    # 429/503 + Retry-After.
                    bp = _backpressure_error(e)
                    if bp is not None:
                        status = bp.code
                        writer.write(_json_response(
                            bp.code, {'error': str(bp)}, bp.headers))
                    else:
                        status = 500
                        writer.write(_json_response(
                            500, {'error': f'{type(e).__name__}: {e}'}))
                    await writer.drain()
                finally:
                    # Access log INSIDE the binding so the record
                    # carries the request identity.
                    logs_lib.access_log(logger, method, route, status)
                    _log_ctx.__exit__(None, None, None)
        except (BrokenPipeError, ConnectionResetError,
                asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (BrokenPipeError, ConnectionResetError, OSError,
                    RuntimeError):
                # RuntimeError: loop already closed during shutdown —
                # the transport dies with it either way.
                pass

    # ------------------------------------------------------------ server

    async def run(self, host: str = '0.0.0.0', port: int = 0,
                  ready: Optional['asyncio.Future'] = None) -> None:
        self._loop = asyncio.get_running_loop()
        server = await asyncio.start_server(self._handle, host, port)
        bound = server.sockets[0].getsockname()[1]
        logger.info(f'async model server on :{bound}')
        if ready is not None:
            ready.set_result(bound)
        async with server:
            await server.serve_forever()


def serve_forever(server: 'model_server_lib.ModelServer',
                  port: int = 0) -> None:
    try:
        asyncio.run(AsyncModelServer(server).run(port=port))
    finally:
        server.close()


def start_background(server: 'model_server_lib.ModelServer',
                     port: int = 0):
    """Tests: run the async front on a daemon thread's event loop;
    returns (port, shutdown_fn)."""
    import threading  # pylint: disable=import-outside-toplevel
    front = AsyncModelServer(server)
    loop = asyncio.new_event_loop()
    ready: 'asyncio.Future' = loop.create_future()
    boot_error: list = []

    def _run() -> None:
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(front.run(port=port, ready=ready))
        except asyncio.CancelledError:
            pass
        except Exception as e:  # pylint: disable=broad-except
            boot_error.append(e)  # e.g. EADDRINUSE before ready
        finally:
            loop.close()

    thread = threading.Thread(target=_run, daemon=True)
    thread.start()
    while not ready.done():
        if not thread.is_alive():
            raise RuntimeError(
                f'async server failed to start: '
                f'{boot_error[0] if boot_error else "unknown"}')
        time.sleep(0.01)

    def shutdown() -> None:
        def _stop() -> None:
            for task in asyncio.all_tasks(loop):
                task.cancel()
        loop.call_soon_threadsafe(_stop)
        thread.join(timeout=10)

    return ready.result(), shutdown
