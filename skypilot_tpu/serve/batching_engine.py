"""Continuous batching engine for the model server.

vLLM-style scheduling, rebuilt TPU-first (no reference equivalent —
SkyPilot ships no serving internals): a FIXED pool of KV-cache slots is
the batch dimension, so every jit'd shape is static.  Requests join a
running batch the moment a slot frees (no wait for the batch to drain),
and one `models.decode.engine_step` call advances every active slot a
token per engine tick — new arrivals ride along with half-finished
generations.

Decode hot loop (the device never waits on Python):
- Token selection happens ON DEVICE inside the jitted step — greedy
  argmax plus per-slot temperature/top-k sampling, stop-set matching,
  and max_new_tokens countdown all live in `decode.engine_step`, so
  tick t+1's input IS tick t's output with zero host transfer.
- Ticks are PIPELINED one deep: the worker dispatches tick t+1 before
  fetching tick t's tokens and reads results one tick behind for
  stream/stop bookkeeping, so host work overlaps device compute.  A
  slot that stops at tick t is already inactive on device when tick
  t+1 runs — the pipeline never decodes past a stop.
- Prompt prefill is CHUNKED: `_admit` splits a long prompt into
  fixed-size chunks interleaved with decode ticks (at most one chunk
  between ticks), so the worst ITL stall any admission can impose on
  running requests is one chunk's compute, not one prompt's.

Exact-prefill trick for static shapes (dense models): the prompt's
first n-1 tokens are prefilled PADDED to a power-of-two bucket
(bounding compile count), the slot is inserted at length n-1, and the
LAST real prompt token is fed through the next batched step — it
overwrites the first pad position and attends only real keys, so
logits match unpadded decode exactly (tests pin this against
decode.generate).  Chunk 0 keeps that flash-prefill path; chunks at
index > 0 run `decode.prefill_chunk` (per-position causal mask), which
preserves the same n-1/last-token trick per chunk.  MoE models instead
prefill the FULL prompt unpadded in one piece (the capacity dispatch
couples every token, so padding, the n-1 split, and chunk boundaries
would all perturb expert drops) and take their first token from the
prefill logits.

Admission is BOUNDED: `max_queue` rejects new submits when the backlog
is full (`QueueFull` -> HTTP 429) and `queue_ttl` expires requests
that waited too long queued (`QueueExpired` -> HTTP 503), so a load
spike degrades with fast, honest rejections instead of unbounded TTFT.

`pipelined=False` keeps the pre-pipeline loop (inline full-prompt
prefill, one host sync per generated token, greedy only) for A/B
benchmarking — `bench_serve.py` reports the speedup against it.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing

logger = sky_logging.init_logger(__name__)

_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
# Queue-wait histogram bucket upper bounds (seconds); the last bucket
# is open-ended.  Surfaced via stats() -> /health for autoscaling.
_WAIT_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

# Process-global registry instruments (observability/metrics.py) —
# what `GET /metrics` on the serving fronts exposes.  Counters are
# process-cumulative (Prometheus semantics: rates come from deltas);
# the per-ENGINE view lives in stats().  Gauges describe the most
# recently constructed engine — one engine per serving process.
_M_TICKS = metrics_lib.counter(
    'skytpu_engine_ticks_total', 'Decode engine ticks dispatched.')
_M_TOKENS = metrics_lib.counter(
    'skytpu_engine_decode_tokens_total',
    'Tokens generated across all requests.')
_M_PREFILL_CHUNKS = metrics_lib.counter(
    'skytpu_engine_prefill_chunks_total',
    'Prompt prefill chunks executed.')
_M_ADMITTED = metrics_lib.counter(
    'skytpu_engine_admitted_total',
    'Requests admitted into a KV slot.')
_M_REJECTED = metrics_lib.counter(
    'skytpu_engine_rejected_total',
    'Requests rejected at admission, by reason.', ('reason',))
_M_QUEUE_DEPTH = metrics_lib.gauge(
    'skytpu_engine_queue_depth', 'Requests waiting for a slot.')
_M_BUSY_SLOTS = metrics_lib.gauge(
    'skytpu_engine_busy_slots', 'KV slots currently decoding.')
_M_SLOTS = metrics_lib.gauge(
    'skytpu_engine_slots', 'Total KV slots in the pool.')
_M_DECODE_RATE = metrics_lib.gauge(
    'skytpu_engine_decode_tokens_per_s',
    'Decode tokens/s over the trailing 10s window.')
_M_QUEUE_WAIT = metrics_lib.histogram(
    'skytpu_engine_queue_wait_seconds',
    'Seconds a request waited queued before admission.',
    buckets=_WAIT_BUCKETS)
_M_TTFT = metrics_lib.histogram(
    'skytpu_engine_ttft_seconds',
    'Submit-to-first-token latency per request.')
_M_ITL = metrics_lib.histogram(
    'skytpu_engine_itl_seconds',
    'Inter-token gaps during decode.',
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0))


class QueueFull(RuntimeError):
    """submit() rejected: the admission queue is at max_queue.

    `retry_after` is the engine's estimate (seconds) of when a slot's
    worth of backlog will have drained — servers surface it as an HTTP
    Retry-After header on the 429.
    """

    def __init__(self, message: str, retry_after: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after = max(1.0, retry_after)


class QueueExpired(RuntimeError):
    """The request sat queued past queue_ttl and was never admitted
    (servers map this to 503 + Retry-After)."""

    def __init__(self, message: str, retry_after: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after = max(1.0, retry_after)


class _Request:

    def __init__(self, prompt_ids: List[int], max_new_tokens: int,
                 stop_token, temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0,
                 request_id: Optional[str] = None) -> None:
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = max_new_tokens
        # Per-request phase trace (queue/prefill/TTFT/ITL/total); the
        # id arrives via X-SkyTPU-Request-Id or is generated here.
        self.span = tracing.RequestSpan(request_id)
        self.request_id = self.span.request_id
        # stop_token: None, a single id, or any iterable of ids (the
        # tokenizer's multi-EOS stop set — instruct checkpoints stop at
        # chat turn-end markers, not just the model-level EOS).
        if stop_token is None:
            self.stop_ids = frozenset()
        elif isinstance(stop_token, int):
            self.stop_ids = frozenset({stop_token})
        else:
            self.stop_ids = frozenset(int(t) for t in stop_token)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.submit_time = time.monotonic()
        self.done = threading.Event()
        self.tokens: List[int] = []
        self.error: Optional[Exception] = None
        self.cancelled = False
        # Streaming consumers read tokens as they are produced; the
        # None sentinel marks the end of the stream.
        self._live: 'queue.Queue[Optional[int]]' = queue.Queue()
        # _finish can race (worker finishing vs stop() failing-fast vs
        # submit() losing the stop race): first caller wins, later
        # calls are no-ops — otherwise two None sentinels truncate a
        # stream() and a success can be overwritten with an error.
        self._state_lock = threading.Lock()
        # Event-loop bridges (serve/async_server.py): called with each
        # token and a final None, from the engine worker thread, under
        # the state lock — watchers must be cheap and non-blocking
        # (call_soon_threadsafe qualifies).
        self._watchers: List[Any] = []
        # Set by the engine at submit(): finished spans land here.
        self._span_store: Optional[tracing.SpanStore] = None

    def add_watcher(self, fn) -> None:
        """Subscribe fn(token|None) to this request's token stream;
        tokens already produced are replayed first, so late subscribers
        never miss a prefix (the admission path can push the first
        token before the caller gets the request handle back)."""
        with self._state_lock:
            for token in self.tokens:
                fn(token)
            if self.done.is_set():
                fn(None)
            else:
                self._watchers.append(fn)

    def _push(self, token: int) -> None:
        with self._state_lock:
            if self.done.is_set():
                # stop() already finished this request; a worker still
                # mid-tick must not append past the sentinel.
                return
            gap = self.span.mark_token()
            if gap is None:
                if self.span.ttft_s is not None:
                    _M_TTFT.observe(self.span.ttft_s)
            else:
                _M_ITL.observe(gap)
            self.tokens.append(token)
            self._live.put(token)
            self._notify(token)

    def _finish(self, error: Optional[Exception] = None) -> None:
        with self._state_lock:
            if self.done.is_set():
                return
            self.error = error
            self.done.set()
            if error is not None:
                status = type(error).__name__
            elif self.cancelled:
                status = 'cancelled'
            else:
                status = 'ok'
            self.span.finish(status)
            if self._span_store is not None:
                self._span_store.add(self.span)
            self._live.put(None)
            self._notify(None)
            self._watchers.clear()

    def _notify(self, token: Optional[int]) -> None:
        # A raising watcher (e.g. call_soon_threadsafe on a closed
        # event loop at shutdown) must not propagate into the engine
        # worker — that would fail the WHOLE engine for one dead
        # subscriber.  Drop it instead.
        for fn in list(self._watchers):
            try:
                fn(token)
            except Exception:  # pylint: disable=broad-except
                try:
                    self._watchers.remove(fn)
                except ValueError:
                    pass

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError('generation timed out')
        if self.error is not None:
            raise self.error
        return self.tokens

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as the engine produces them."""
        while True:
            token = self._live.get(timeout=timeout)
            if token is None:
                if self.error is not None:
                    raise self.error
                return
            yield token

    def cancel(self) -> None:
        """Stop generating for this request (client went away); the
        engine frees the slot on its next tick."""
        self.cancelled = True


class _Slot:

    def __init__(self) -> None:
        self.request: Optional[_Request] = None
        self.next_token = 0          # legacy (unpipelined) loop only

    @property
    def active(self) -> bool:
        return self.request is not None


class _PendingPrefill:
    """A dense prompt mid-chunked-prefill: the slot is reserved but
    does not join decode ticks until every chunk has run."""

    def __init__(self, slot_id: int, request: _Request,
                 n_target: int) -> None:
        self.slot_id = slot_id
        self.request = request
        self.n_target = n_target     # tokens to prefill (n-1, dense)
        self.consumed = 0
        self.cache: Optional[Dict[str, Any]] = None  # private [*,1,..]


class ContinuousBatchingEngine:
    """Submit() from any thread; one worker thread owns the device."""

    def __init__(self, cfg, params, *, max_len: int = 512,
                 slots: int = 4, prefill_chunk: int = 512,
                 max_queue: int = 0,
                 queue_ttl: Optional[float] = None,
                 max_top_k: int = 64, max_stop_ids: int = 16,
                 pipelined: bool = True, mesh=None) -> None:
        import functools

        import jax
        import jax.numpy as jnp

        from skypilot_tpu.models import decode

        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.max_queue = int(max_queue)          # 0 = unbounded
        self.queue_ttl = queue_ttl               # None = no expiry
        self.max_top_k = int(max_top_k)
        self.max_stop_ids = int(max_stop_ids)
        self.pipelined = pipelined
        self._jnp = jnp
        self._jax = jax
        self._slots = [_Slot() for _ in range(slots)]
        self._cache = decode.init_slot_cache(cfg, slots, max_len)
        self._state = decode.init_engine_state(slots, max_stop_ids)
        if mesh is not None:
            # Tensor-sharded serving: place the slot KV pool and the
            # tiny per-slot state explicitly (kv_heads on 'tensor',
            # state replicated) instead of leaving GSPMD to guess from
            # the first donated step.
            from skypilot_tpu.parallel import sharding as sharding_lib
            self._cache = jax.device_put(
                self._cache, sharding_lib.slot_cache_sharding(mesh))
            self._state = jax.device_put(
                self._state, sharding_lib.engine_state_sharding(mesh))
        self._tokens = jnp.zeros((slots, 1), jnp.int32)  # legacy loop
        self._queue: Deque[_Request] = collections.deque()
        self._cond = threading.Condition()
        self._stop = threading.Event()

        self._step = jax.jit(
            functools.partial(decode.engine_step, cfg,
                              max_top_k=self.max_top_k),
            donate_argnums=(2,))
        self._legacy_step = jax.jit(
            lambda p, t, c: decode.batched_step(cfg, p, t, c),
            donate_argnums=(2,))
        # Jitted prefill: one compile per prompt-length bucket (the
        # whole point of the bucket padding), not eager per-op dispatch
        # per admission.
        self._prefill = jax.jit(
            lambda params, toks: decode.prefill(cfg, params, toks,
                                                max_len=max_len))
        # Chunk continuation at index > 0 (masked per-position causal
        # path): one compile per chunk width; the private prefill cache
        # is donated so XLA extends it in place.
        self._prefill_chunk = jax.jit(
            lambda params, toks, cache: decode.prefill_chunk(
                cfg, params, toks, cache),
            donate_argnums=(2,))
        # Jitted in-place slot adoption: eager dynamic_update_slice
        # would materialize two full copies of the pool cache per
        # admission; donation lets XLA update it in place.
        self._insert = jax.jit(decode.insert_prefill,
                               donate_argnums=(0,))
        # One dispatch per admission for the whole per-slot state write
        # (NOT donated: the previous tick's token buffer may still be
        # pending its one-tick-behind host read).
        self._admit_state = jax.jit(decode.admit_slot_state)
        self._sample_one = jax.jit(
            functools.partial(decode.batched_sample,
                              max_top_k=self.max_top_k))
        self._failed: Optional[Exception] = None

        # ---- metrics (updated under _metrics_lock; read by stats()).
        # These are the per-ENGINE view; every update is mirrored into
        # the process-global registry instruments above (what
        # GET /metrics exposes).
        self._metrics_lock = threading.Lock()
        self._tokens_generated = 0
        self._ticks = 0
        self._prefill_chunks = 0
        self._queue_full_rejections = 0
        self._queue_ttl_expiries = 0
        self._queue_wait_hist = [0] * (len(_WAIT_BUCKETS) + 1)
        self._rate_window: Deque[Tuple[float, int]] = collections.deque()
        # Finished per-request spans (queue/prefill/TTFT/ITL/total),
        # bounded; surfaced via stats()['recent_spans'] and span().
        self._spans = tracing.SpanStore()
        _M_SLOTS.set(slots)
        _M_BUSY_SLOTS.set(0)
        _M_QUEUE_DEPTH.set(0)

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ public

    def submit(self, prompt_ids: List[int], max_new_tokens: int,
               stop_token=None, sampling=None,
               request_id: Optional[str] = None) -> _Request:
        """stop_token: None, one id, or an iterable of ids — the
        request finishes at the FIRST generated member of the set
        (multi-EOS: model-level EOS + chat turn-end markers).

        sampling: optional models.decode.SamplingConfig.  temperature
        <= 0 decodes greedily (the deterministic serving default);
        temperature > 0 samples on device with per-request top_k/seed —
        deterministic for a given seed (the slot's key chain splits
        once per generated token, independent of other traffic).

        request_id: the propagated X-SkyTPU-Request-Id (generated when
        absent); names the request's span record and timeline events."""
        if not prompt_ids:
            raise ValueError('empty prompt')
        if max_new_tokens < 1:
            raise ValueError(
                f'max_new_tokens must be >= 1, got {max_new_tokens}')
        if len(prompt_ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f'prompt {len(prompt_ids)} + new {max_new_tokens} '
                f'exceeds max_len {self.max_len}')
        temperature, top_k, seed = 0.0, 0, 0
        if sampling is not None:
            temperature = float(sampling.temperature)
            top_k = int(sampling.top_k)
            seed = int(getattr(sampling, 'seed', 0))
        if top_k > self.max_top_k:
            raise ValueError(
                f'top_k {top_k} > engine max_top_k {self.max_top_k}')
        if temperature > 0.0 and not self.pipelined:
            raise ValueError(
                'the legacy (pipelined=False) loop serves greedy '
                'decoding only')
        request = _Request(prompt_ids, max_new_tokens, stop_token,
                           temperature=temperature, top_k=top_k,
                           seed=seed, request_id=request_id)
        request._span_store = self._spans  # pylint: disable=protected-access
        if len(request.stop_ids) > self.max_stop_ids:
            raise ValueError(
                f'{len(request.stop_ids)} stop ids > engine '
                f'max_stop_ids {self.max_stop_ids}')
        if self._stop.is_set() or self._failed is not None:
            raise RuntimeError('batching engine is stopped'
                               if self._failed is None else
                               f'batching engine failed: {self._failed}')
        with self._cond:
            if self.max_queue and len(self._queue) >= self.max_queue:
                with self._metrics_lock:
                    self._queue_full_rejections += 1
                _M_REJECTED.labels(reason='queue_full').inc()
                raise QueueFull(
                    f'admission queue full ({self.max_queue} waiting); '
                    'retry later', retry_after=self._drain_estimate())
            self._queue.append(request)
            _M_QUEUE_DEPTH.set(len(self._queue))
            self._cond.notify()
        if self._stop.is_set():
            # Lost the race with stop(): its drain may have already run,
            # so fail this request directly (idempotent via the event).
            if not request.done.is_set():
                request._finish(  # pylint: disable=protected-access
                    RuntimeError('batching engine stopped'))
        return request

    def generate(self, prompt_ids: List[int], max_new_tokens: int,
                 stop_token=None, sampling=None,
                 timeout: float = 600.0) -> List[int]:
        return self.submit(prompt_ids, max_new_tokens, stop_token,
                           sampling=sampling).result(timeout)

    def _drain_estimate(self) -> float:
        """Rough seconds until one queue position frees: backlog size
        over the recent decode rate (floor 1s — it feeds Retry-After)."""
        rate = self._decode_rate()
        if rate <= 0:
            return 1.0
        avg_new = 32.0  # no per-request oracle; a slot's typical budget
        return max(1.0, len(self._queue) * avg_new /
                   (rate * max(1, len(self._slots))))

    def _decode_rate(self) -> float:
        with self._metrics_lock:
            if not self._rate_window:
                return 0.0
            t0 = self._rate_window[0][0]
            span = time.monotonic() - t0
            total = sum(n for _, n in self._rate_window)
        return total / max(span, 1e-3)

    def stats(self) -> Dict[str, Any]:
        """Live scheduling + decode-saturation stats (surfaced via the
        server's /health): queue depth and slot occupancy are the
        scale-out signals, decode_tokens_per_s and the queue-wait
        histogram say whether the replica is decode-bound rather than
        merely popular (serve/autoscalers.py consumes busy/slots as
        replica load)."""
        busy = sum(1 for s in self._slots if s.active)
        with self._metrics_lock:
            hist = {}
            for i, bound in enumerate(_WAIT_BUCKETS):
                hist[f'<{bound}s'] = self._queue_wait_hist[i]
            hist[f'>={_WAIT_BUCKETS[-1]}s'] = self._queue_wait_hist[-1]
            stats = {
                'slots': len(self._slots),
                'busy_slots': busy,
                'queued_requests': len(self._queue),
                'tokens_generated': self._tokens_generated,
                'failed': self._failed is not None,
                'ticks': self._ticks,
                'prefill_chunks': self._prefill_chunks,
                'queue_full_rejections': self._queue_full_rejections,
                'queue_ttl_expiries': self._queue_ttl_expiries,
                'queue_wait_hist': hist,
                'max_queue': self.max_queue,
                'prefill_chunk': self.prefill_chunk,
                'pipelined': self.pipelined,
            }
        rate = round(self._decode_rate(), 3)
        stats['decode_tokens_per_s'] = rate
        # Per-request phase traces (newest first) — the "why was THIS
        # request slow" answer, keyed by X-SkyTPU-Request-Id.
        stats['recent_spans'] = self._spans.recent()
        # Freshen the scrape-time gauges so /metrics agrees with
        # /health no matter which is polled.
        _M_SLOTS.set(stats['slots'])
        _M_BUSY_SLOTS.set(busy)
        _M_QUEUE_DEPTH.set(stats['queued_requests'])
        _M_DECODE_RATE.set(rate)
        return stats

    def span(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The finished span record for a request id (None while the
        request is still running or once it aged out of the store)."""
        return self._spans.get(request_id)

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self._thread.join(timeout=10)
        # Fail fast for anything still queued or in flight — callers
        # must not sit out their full result() timeout at shutdown.
        shutdown_error = RuntimeError('batching engine stopped')
        while True:
            with self._cond:
                if not self._queue:
                    break
                request = self._queue.popleft()
            request._finish(shutdown_error)  # pylint: disable=protected-access
        for slot in self._slots:
            if slot.request is not None:
                slot.request._finish(shutdown_error)  # pylint: disable=protected-access
                slot.request = None

    # ------------------------------------------------------------ metrics

    def _record_tokens(self, n: int) -> None:
        now = time.monotonic()
        with self._metrics_lock:
            self._tokens_generated += n
            self._rate_window.append((now, n))
            while (self._rate_window and
                   now - self._rate_window[0][0] > 10.0):
                self._rate_window.popleft()
        _M_TOKENS.inc(n)
        _M_DECODE_RATE.set(round(self._decode_rate(), 3))

    def _record_queue_wait(self, request: _Request) -> None:
        request.span.mark_admitted()
        wait = time.monotonic() - request.submit_time
        _M_ADMITTED.inc()
        _M_QUEUE_WAIT.observe(wait)
        with self._metrics_lock:
            for i, bound in enumerate(_WAIT_BUCKETS):
                if wait < bound:
                    self._queue_wait_hist[i] += 1
                    return
            self._queue_wait_hist[-1] += 1

    # ------------------------------------------------------------ worker

    def _bucket(self, n: int) -> int:
        for b in _PREFILL_BUCKETS:
            if n <= b:
                return b
        return n

    def _pop_request(self) -> Optional[_Request]:
        """Pop the next live queued request, expiring stale ones."""
        while True:
            with self._cond:
                if not self._queue:
                    return None
                request = self._queue.popleft()
            if request.cancelled:
                request._finish()  # pylint: disable=protected-access
                continue
            if (self.queue_ttl is not None and
                    time.monotonic() - request.submit_time >
                    self.queue_ttl):
                self._record_expiry(1)
                request._finish(QueueExpired(  # pylint: disable=protected-access
                    f'request expired after {self.queue_ttl}s queued',
                    retry_after=self._drain_estimate()))
                continue
            self._record_queue_wait(request)
            with self._cond:
                _M_QUEUE_DEPTH.set(len(self._queue))
            return request

    def _record_expiry(self, n: int) -> None:
        with self._metrics_lock:
            self._queue_ttl_expiries += n
        _M_REJECTED.labels(reason='queue_expired').inc(n)

    def _expire_queued(self) -> None:
        """Fail requests that outlived queue_ttl while still queued —
        without this a saturated engine leaves them waiting out their
        whole client timeout."""
        if self.queue_ttl is None:
            return
        now = time.monotonic()
        expired = []
        with self._cond:
            if not self._queue:
                return
            keep: Deque[_Request] = collections.deque()
            for request in self._queue:
                if now - request.submit_time > self.queue_ttl:
                    expired.append(request)
                else:
                    keep.append(request)
            self._queue = keep
            _M_QUEUE_DEPTH.set(len(keep))
        if expired:
            self._record_expiry(len(expired))
        for request in expired:
            request._finish(QueueExpired(  # pylint: disable=protected-access
                f'request expired after {self.queue_ttl}s queued',
                retry_after=self._drain_estimate()))

    # ----------------------------------------------- pipelined admission

    def _start_admission(self, slot_id: int, request: _Request
                         ) -> Optional[_PendingPrefill]:
        """Begin admitting `request` into `slot_id`.  Returns a
        _PendingPrefill when chunks remain, None when the slot is live
        (or the request finished at admission)."""
        jnp = self._jnp
        slot = self._slots[slot_id]
        prompt = request.prompt_ids
        n = len(prompt)
        if self.cfg.n_experts > 0 and n > 0:
            # MoE: the capacity dispatch couples EVERY prompt token, so
            # pad tokens, an n-1/last-token split, and chunk boundaries
            # would all change which tokens drop — only a full-prompt
            # unpadded prefill matches the single-sequence reference.
            # The first generated token therefore comes from the
            # prefill logits (one compile per distinct MoE prompt
            # length), selected with the same key chain a tick uses.
            t_prefill = time.perf_counter()
            logits, pre = self._prefill(
                self.params, jnp.asarray([prompt], jnp.int32))
            request.span.mark_prefill_chunk(
                time.perf_counter() - t_prefill)
            self._cache = self._insert(self._cache, slot_id, pre, n)
            key = self._jax.random.PRNGKey(request.seed)
            carry, sub = self._jax.random.split(key)
            first = int(self._sample_one(
                logits, sub[None],
                jnp.asarray([request.temperature], jnp.float32),
                jnp.asarray([request.top_k], jnp.int32))[0])
            request._push(first)  # pylint: disable=protected-access
            self._record_tokens(1)
            if (request.max_new_tokens <= 1 or
                    first in request.stop_ids):
                request._finish()  # pylint: disable=protected-access
                return None
            slot.request = request
            self._activate(slot_id, request, first, n,
                           remaining=request.max_new_tokens - 1,
                           key=carry)
            return None
        if n <= 1:
            # Single-token prompt: empty slot; stale keys are masked
            # (per-position causal mask) and position 0 is overwritten
            # by the first step's write.
            self._cache = dict(
                self._cache,
                lengths=self._cache['lengths'].at[slot_id].set(0))
            slot.request = request
            self._activate(slot_id, request, int(prompt[-1]), 0,
                           remaining=request.max_new_tokens,
                           key=self._jax.random.PRNGKey(request.seed))
            return None
        # Dense: prefill tokens [0, n-1) in chunks; the last REAL
        # prompt token is fed through the first batched step (it
        # overwrites the first pad position and attends only real
        # keys, so logits match unpadded decode exactly).
        slot.request = request
        pending = _PendingPrefill(slot_id, request, n - 1)
        return pending

    def _advance_prefill(self, pending: _PendingPrefill) -> bool:
        """Run ONE chunk of a pending prefill (this is the whole point:
        an admission stalls running decodes by at most one chunk).
        Returns True when the prefill completed and the slot went live.
        """
        jnp = self._jnp
        request = pending.request
        if request.cancelled:
            request._finish()  # pylint: disable=protected-access
            self._slots[pending.slot_id].request = None
            return True  # pending is finished (slot freed)
        import numpy as np  # pylint: disable=import-outside-toplevel
        t_chunk0 = time.perf_counter()
        n_target = pending.n_target
        chunk = self.prefill_chunk
        if pending.cache is None:
            # Chunk 0: flash prefill from index 0 into a fresh private
            # cache.  Width = the bucket of min(n_target, chunk) so
            # short prompts keep today's bucket-bounded compile count;
            # pad keys land at positions >= the real length where the
            # causal mask hides them (and the first one is overwritten
            # by the real last token's step).  Padding is staged in
            # NUMPY: eager `.at[:n].set` would compile a tiny scatter
            # per distinct prompt length, right on the admission path.
            take = min(n_target, chunk)
            bucket = min(self._bucket(take), self.max_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :take] = request.prompt_ids[:take]
            _, pending.cache = self._prefill(self.params,
                                             jnp.asarray(padded))
            # The padded flash cache advanced index to `bucket`; chunk
            # continuations must write at the REAL consumed length.
            pending.cache = dict(pending.cache,
                                 index=jnp.asarray(take, jnp.int32))
            pending.consumed = take
        else:
            # Chunk i>0: masked per-position-causal continuation at
            # index = consumed.  Always `chunk` wide (one compile);
            # the final partial chunk is zero-padded — pad positions
            # are beyond every real query's causal horizon and each is
            # overwritten by the decode step that reaches it.
            start = pending.consumed
            take = min(n_target - start, chunk)
            piece = np.zeros((1, chunk), np.int32)
            piece[0, :take] = request.prompt_ids[start:start + take]
            _, pending.cache = self._prefill_chunk(
                self.params, jnp.asarray(piece), pending.cache)
            pending.cache = dict(
                pending.cache,
                index=jnp.asarray(start + take, jnp.int32))
            pending.consumed = start + take
        request.span.mark_prefill_chunk(time.perf_counter() - t_chunk0)
        _M_PREFILL_CHUNKS.inc()
        with self._metrics_lock:
            self._prefill_chunks += 1
        if pending.consumed < n_target:
            return False
        # All chunks in: adopt the private cache into the slot pool and
        # join the next decode tick at length n-1 with the last REAL
        # prompt token as input.
        self._cache = self._insert(self._cache, pending.slot_id,
                                   pending.cache, n_target)
        self._activate(pending.slot_id, request,
                       int(request.prompt_ids[-1]), n_target,
                       remaining=request.max_new_tokens,
                       key=self._jax.random.PRNGKey(request.seed))
        return True

    def _activate(self, slot_id: int, request: _Request, token: int,
                  length: int, *, remaining: int, key) -> None:
        """Flip a slot live in the device state (one jitted dispatch)."""
        del length  # cache lengths are set by insert/admission paths
        jnp = self._jnp
        stop_row = [-1] * self.max_stop_ids
        for i, sid in enumerate(sorted(request.stop_ids)):
            stop_row[i] = sid
        self._state = self._admit_state(
            self._state, slot_id, token, remaining,
            jnp.asarray(stop_row, jnp.int32), key,
            request.temperature, request.top_k)

    def _deactivate(self, slot_ids: List[int]) -> None:
        """Host-forced slot shutdown (cancel): flip active off so the
        next tick freezes the slot."""
        active = self._state['active']
        for i in slot_ids:
            active = active.at[i].set(False)
        self._state = dict(self._state, active=active)

    # ------------------------------------------------- pipelined worker

    def _run(self) -> None:
        if not self.pipelined:
            self._run_legacy()
            return
        import numpy as np  # pylint: disable=import-outside-toplevel
        # One in-flight tick: (state_handles, finished_handle,
        # [(slot_id, request), ...]) — read one tick behind.
        inflight: Optional[Tuple[Any, Any, List[Tuple[int, Any]]]] = None
        pending_prefills: Deque[_PendingPrefill] = collections.deque()
        live: Dict[int, _Request] = {}   # slot -> decoding request
        while not self._stop.is_set():
            try:
                self._expire_queued()
                # Cancelled live requests: freeze their slots on device
                # before the next dispatch, free them for admission.
                cancelled = [i for i, r in live.items() if r.cancelled]
                if cancelled:
                    self._deactivate(cancelled)
                    for i in cancelled:
                        request = live.pop(i)
                        self._slots[i].request = None
                        request._finish()  # pylint: disable=protected-access
                # Admissions: hand free slots to queued requests.  The
                # prompt's chunks run interleaved with ticks below.
                free = [i for i, s in enumerate(self._slots)
                        if not s.active]
                for slot_id in free:
                    request = self._pop_request()
                    if request is None:
                        break
                    pending = self._start_admission(slot_id, request)
                    if pending is not None:
                        pending_prefills.append(pending)
                    elif self._slots[slot_id].request is not None:
                        live[slot_id] = request
                # At most ONE prefill chunk between ticks — the bound
                # on the ITL stall an admission can impose.
                if pending_prefills:
                    pending = pending_prefills.popleft()
                    done = self._advance_prefill(pending)
                    if done:
                        if self._slots[pending.slot_id].request is not None:
                            live[pending.slot_id] = pending.request
                    else:
                        pending_prefills.append(pending)
                # Dispatch tick t+1 BEFORE reading tick t: the host's
                # token fetch and stream bookkeeping below overlap the
                # device's compute of this new step.
                dispatched = None
                if live:
                    self._state, self._cache, finished = self._step(
                        self.params, self._state, self._cache)
                    dispatched = (self._state, finished,
                                  list(live.items()))
                if inflight is not None:
                    state_t, finished_t, snapshot = inflight
                    toks = np.asarray(state_t['tokens'])
                    fins = np.asarray(finished_t)
                    pushed = 0
                    for slot_id, request in snapshot:
                        if request.done.is_set():
                            # Finished in an earlier tick (device froze
                            # the slot); this tick's value is a repeat.
                            continue
                        request._push(int(toks[slot_id]))  # pylint: disable=protected-access
                        pushed += 1
                        if fins[slot_id]:
                            live.pop(slot_id, None)
                            self._slots[slot_id].request = None
                            request._finish()  # pylint: disable=protected-access
                    if pushed:
                        self._record_tokens(pushed)
                    with self._metrics_lock:
                        self._ticks += 1
                    _M_TICKS.inc()
                    _M_BUSY_SLOTS.set(
                        sum(1 for s in self._slots if s.active))
                inflight = dispatched
                if (inflight is None and not live and
                        not pending_prefills):
                    with self._cond:
                        if not self._queue and not self._stop.is_set():
                            self._cond.wait(timeout=0.05)
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('batching engine tick failed')
                # The jit'd step donates the slot cache — after a
                # failure mid-step the cache buffers may be invalid, so
                # the engine CANNOT safely continue: fail everything in
                # flight, mark failed (submit() rejects from now on),
                # and exit the worker.
                self._fail_everything(e)
                return

    # --------------------------------------------------- legacy worker

    def _admit_legacy(self, slot_id: int, request: _Request) -> None:
        """Pre-pipeline admission: the WHOLE prompt prefills inline
        (one long stall for every running request — what chunked
        prefill bounds)."""
        if request.cancelled:
            request._finish()  # pylint: disable=protected-access
            return
        jnp = self._jnp
        slot = self._slots[slot_id]
        prompt = request.prompt_ids
        n = len(prompt)
        if self.cfg.n_experts > 0 and n > 0:
            logits, pre = self._prefill(
                self.params, jnp.asarray([prompt], jnp.int32))
            self._cache = self._insert(self._cache, slot_id, pre, n)
            first = int(jnp.argmax(logits[0]))
            request._push(first)  # pylint: disable=protected-access
            self._record_tokens(1)
            if (request.max_new_tokens <= 1 or
                    first in request.stop_ids):
                request._finish()  # pylint: disable=protected-access
                return
            slot.request = request
            slot.next_token = first
            return
        if n > 1:
            bucket = min(self._bucket(n - 1), self.max_len)
            padded = jnp.zeros((1, bucket), jnp.int32)
            padded = padded.at[0, :n - 1].set(
                jnp.asarray(prompt[:-1], jnp.int32))
            _, pre = self._prefill(self.params, padded)
            self._cache = self._insert(self._cache, slot_id, pre, n - 1)
        else:
            self._cache = dict(
                self._cache,
                lengths=self._cache['lengths'].at[slot_id].set(0))
        slot.request = request
        slot.next_token = int(prompt[-1])

    def _tick_legacy(self) -> None:
        """Pre-pipeline tick: eager per-slot token staging, one host
        sync per generated token, greedy only.  Kept as the A/B
        baseline `bench_serve.py` measures the pipelined loop against
        (and as a debugging fallback)."""
        jnp = self._jnp
        active = [i for i, s in enumerate(self._slots) if s.active]
        for i in active:
            req = self._slots[i].request
            if req.cancelled:
                self._slots[i].request = None
                req._finish()  # pylint: disable=protected-access
        active = [i for i, s in enumerate(self._slots) if s.active]
        if not active:
            return
        tokens = self._tokens
        for i in active:
            tokens = tokens.at[i, 0].set(self._slots[i].next_token)
        logits, self._cache = self._legacy_step(self.params, tokens,
                                                self._cache)
        import numpy as np  # pylint: disable=import-outside-toplevel
        nxt = np.asarray(jnp.argmax(logits, axis=-1))  # one host sync
        pushed = 0
        for i in active:
            slot = self._slots[i]
            request = slot.request
            token = int(nxt[i])
            request._push(token)  # pylint: disable=protected-access
            pushed += 1
            finished = (len(request.tokens) >= request.max_new_tokens or
                        token in request.stop_ids)
            if finished:
                slot.request = None
                request._finish()  # pylint: disable=protected-access
            else:
                slot.next_token = token
        self._tokens = tokens
        self._record_tokens(pushed)
        with self._metrics_lock:
            self._ticks += 1
        _M_TICKS.inc()
        _M_BUSY_SLOTS.set(sum(1 for s in self._slots if s.active))

    def _run_legacy(self) -> None:
        while not self._stop.is_set():
            try:
                self._expire_queued()
                idle = not any(s.active for s in self._slots)
                free = [i for i, s in enumerate(self._slots)
                        if not s.active]
                for slot_id in free:
                    request = self._pop_request()
                    if request is None:
                        if idle:
                            with self._cond:
                                if (not self._queue and
                                        not self._stop.is_set()):
                                    self._cond.wait(timeout=0.05)
                            request = self._pop_request()
                        if request is None:
                            break
                    try:
                        self._admit_legacy(slot_id, request)
                        idle = False
                    except Exception as e:  # pylint: disable=broad-except
                        request._finish(e)  # pylint: disable=protected-access
                self._tick_legacy()
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('batching engine tick failed')
                self._fail_everything(e)
                return

    # ------------------------------------------------------------ failure

    def _fail_everything(self, e: Exception) -> None:
        self._failed = e
        self._stop.set()
        for slot in self._slots:
            if slot.request is not None:
                slot.request._finish(RuntimeError(  # pylint: disable=protected-access
                    f'batching engine failed: {e}'))
                slot.request = None
        while True:
            with self._cond:
                if not self._queue:
                    break
                request = self._queue.popleft()
            request._finish(RuntimeError(  # pylint: disable=protected-access
                f'batching engine failed: {e}'))
