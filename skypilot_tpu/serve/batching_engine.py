"""Continuous batching engine for the model server.

vLLM-style scheduling, rebuilt TPU-first (no reference equivalent —
SkyPilot ships no serving internals): a FIXED pool of KV-cache slots is
the batch dimension, so every jit'd shape is static.  Requests join a
running batch the moment a slot frees (no wait for the batch to drain),
and one `models.decode.engine_step` call advances every active slot a
token per engine tick — new arrivals ride along with half-finished
generations.

This module is the compatibility FACADE over the engine's three parts
(split per ROADMAP before the page pool landed):

- `serve/scheduler.py`  — Request handles, the bounded/TTL'd admission
  queue (QueueFull -> 429, QueueExpired -> 503), slot bookkeeping.
- `serve/cache_manager.py` — the paged-KV host side: page pool
  allocator (refcounts/pins/COW, null page), chain-hashed prefix
  cache with LRU eviction, per-slot page ownership.
- `serve/sampler.py`    — submit-side sampling validation + the jitted
  per-slot admission staging.

Existing imports keep working: `batching_engine.QueueFull`,
`batching_engine._Request`, `ContinuousBatchingEngine`, ... are all
re-exported here.

KV cache modes:

- DENSE (default, `kv_pages=None`): one `[L, slots, h_kv, max_len, d]`
  cache — every slot reserves max_len positions, so concurrency is
  bounded by the worst-case sequence length.
- PAGED (`kv_pages=N`): a pool of N pages `[L, N, h_kv, page_size, d]`
  with per-slot block tables (`models/decode.paged_engine_step`
  gathers pages by table index inside the jitted tick).  Memory is
  bounded by the tokens a request can actually touch, decoupling slot
  count from max_len; admission allocates `ceil((prompt + max_new - 1)
  / page_size)` pages and BACKPRESSURES (QueueFull/429 + Retry-After)
  on pool exhaustion instead of failing the engine.  Pages free on
  completion, cancel, and TTL expiry.  `quantize_kv=True` stores pages
  as int8 with per-page-per-head scales (~2x more tokens per byte;
  dequant fuses into the attention einsum).  `prefix_caching=True`
  registers every FULL prefilled prompt page under a chain hash, so
  requests sharing a system prompt adopt the cached pages instead of
  re-prefilling — TTFT on a prefix hit collapses to the tail chunks.
  Sessions diverging mid-page stop matching at the divergence page and
  each writes its own copy (full pages are immutable once written, so
  shared pages are never mutated).

Decode hot loop (the device never waits on Python):
- Token selection happens ON DEVICE inside the jitted step — greedy
  argmax plus per-slot temperature/top-k sampling, stop-set matching,
  and max_new_tokens countdown all live in `decode.engine_step`, so
  tick t+1's input IS tick t's output with zero host transfer.
- Ticks are PIPELINED one deep: the worker dispatches tick t+1 before
  fetching tick t's tokens and reads results one tick behind for
  stream/stop bookkeeping, so host work overlaps device compute.  A
  slot that stops at tick t is already inactive on device when tick
  t+1 runs — the pipeline never decodes past a stop.
- Prompt prefill is CHUNKED: `_admit` splits a long prompt into
  fixed-size chunks interleaved with decode ticks (at most one chunk
  between ticks), so the worst ITL stall any admission can impose on
  running requests is one chunk's compute, not one prompt's.

Self-speculative decoding (`spec_tokens=k > 0`, paged engines only): a
per-slot host-side n-gram/prompt-lookup drafter
(`serve/sampler.NgramDrafter`) proposes k tokens, ONE batched verify
tick (`decode.paged_spec_engine_step`) scores all of them against the
paged cache, and each slot emits its longest exactly-matching draft
prefix plus the verified bonus token.  Token streams are byte-identical
to spec-off — greedy AND seeded sampling — because every emitted token
is the engine's own verified choice; drafts only decide how many land
per dispatch.  Rejected drafts' KV writes land beyond the slot's
advanced length (overwritten by later ticks before any query attends
them) or, past the block table, in the pool's reserved null page.
Spec mode runs ticks SYNCHRONOUSLY (the drafter needs the tokens a
tick just emitted), trading the one-deep pipeline for up to k+1 tokens
per dispatch.  The paged attention inside every tick runs the Pallas
paged-attention kernel where it can (`SKYTPU_DECODE_KERNEL=
pallas|gather`, ops/paged_attention.py) with the jnp gather fallback
elsewhere — both parity-pinned against the dense engine.

Exact-prefill trick for static shapes (dense models): the prompt's
first n-1 tokens are prefilled PADDED to a power-of-two bucket
(bounding compile count), the slot is inserted at length n-1, and the
LAST real prompt token is fed through the next batched step — it
overwrites the first pad position and attends only real keys, so
logits match unpadded decode exactly (tests pin this against
decode.generate).  Chunk 0 keeps that flash-prefill path; chunks at
index > 0 run `decode.prefill_chunk` (per-position causal mask), which
preserves the same n-1/last-token trick per chunk.  A prefix-cache hit
replaces chunk 0: the cached pages seed the private prefill cache and
only the unmatched tail chunks run.  MoE models instead prefill the
FULL prompt unpadded in one piece (the capacity dispatch couples every
token, so padding, the n-1 split, and chunk boundaries would all
perturb expert drops) and take their first token from the prefill
logits; the capacity dispatch also couples KV to the whole prompt, so
MoE skips prefix reuse (pages still pool).

Admission is BOUNDED: `max_queue` rejects new submits when the backlog
is full (`QueueFull` -> HTTP 429) and `queue_ttl` expires requests
that waited too long queued (`QueueExpired` -> HTTP 503), so a load
spike degrades with fast, honest rejections instead of unbounded TTFT.

`pipelined=False` keeps the pre-pipeline loop (inline full-prompt
prefill, one host sync per generated token, greedy only, dense cache
only) for A/B benchmarking — `bench_serve.py` reports the speedup
against it.
"""
from __future__ import annotations

import collections
import functools
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.observability import logs as logs_lib
from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import profiling
from skypilot_tpu.observability import tracing
from skypilot_tpu.serve import cache_manager
from skypilot_tpu.serve import handoff as handoff_lib
from skypilot_tpu.serve import sampler as sampler_lib
from skypilot_tpu.serve import scheduler

logger = sky_logging.init_logger(__name__)

# ------------------------------------------------- compatibility facade
QueueFull = scheduler.QueueFull
QueueExpired = scheduler.QueueExpired
DeadlineExceeded = scheduler.DeadlineExceeded
PagesExhausted = cache_manager.PagesExhausted
HandoffError = handoff_lib.HandoffError
HandoffRejected = handoff_lib.HandoffRejected
_Request = scheduler.Request
_Slot = scheduler.Slot
_PendingPrefill = scheduler.PendingPrefill
_WAIT_BUCKETS = scheduler.WAIT_BUCKETS
# Full public surface of the three parts, same names (the facade
# contract `sky lint` pins: facade-missing/facade-stale findings when
# this drifts — see analysis/passes/facade_surface.py).
AdmissionQueue = scheduler.AdmissionQueue
PendingPrefill = scheduler.PendingPrefill
Request = scheduler.Request
RoleBudget = scheduler.RoleBudget
Slot = scheduler.Slot
WAIT_BUCKETS = scheduler.WAIT_BUCKETS
AdmissionPlan = cache_manager.AdmissionPlan
NULL_PAGE = cache_manager.NULL_PAGE
PagePool = cache_manager.PagePool
PagedKVManager = cache_manager.PagedKVManager
PrefixCache = cache_manager.PrefixCache
chunk_hashes = cache_manager.chunk_hashes
NgramDrafter = sampler_lib.NgramDrafter
SlotSampler = sampler_lib.SlotSampler
validate_sampling = sampler_lib.validate_sampling
validate_stop_ids = sampler_lib.validate_stop_ids

_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

# Process-global registry instruments (observability/metrics.py) —
# what `GET /metrics` on the serving fronts exposes.  Counters are
# process-cumulative (Prometheus semantics: rates come from deltas);
# the per-ENGINE view lives in stats().  Gauges describe the most
# recently constructed engine — one engine per serving process.
# Queue/admission instruments live in serve/scheduler.py; page-pool
# and prefix-cache instruments in serve/cache_manager.py.
_M_TICKS = metrics_lib.counter(
    'skytpu_engine_ticks_total', 'Decode engine ticks dispatched.')
_M_TOKENS = metrics_lib.counter(
    'skytpu_engine_decode_tokens_total',
    'Tokens generated across all requests.')
_M_PREFILL_CHUNKS = metrics_lib.counter(
    'skytpu_engine_prefill_chunks_total',
    'Prompt prefill chunks executed.')
_M_BUSY_SLOTS = metrics_lib.gauge(
    'skytpu_engine_busy_slots', 'KV slots currently decoding.')
_M_SLOTS = metrics_lib.gauge(
    'skytpu_engine_slots', 'Total KV slots in the pool.')
_M_DECODE_RATE = metrics_lib.gauge(
    'skytpu_engine_decode_tokens_per_s',
    'Decode tokens/s over the trailing 10s window.')
_M_HANDOFF_EXPORTS = metrics_lib.counter(
    'skytpu_engine_handoff_exports_total',
    'KV page exports served (the prefill side of a handoff).')
_M_HANDOFF_IMPORTS = metrics_lib.counter(
    'skytpu_engine_handoff_imports_total',
    'KV page imports (the decode side of a handoff), by result.',
    ('result',))
_M_DEADLINE_REAPED = metrics_lib.counter(
    'skytpu_engine_deadline_reaped_total',
    'Decoding requests cancelled mid-generation because their '
    'X-SkyTPU-Deadline-Ms passed (slot and KV pages freed).')
_M_SPEC_PROPOSED = metrics_lib.counter(
    'skytpu_engine_spec_proposed_tokens_total',
    'Draft tokens proposed to speculative verify ticks (k per live '
    'slot per tick).')
_M_SPEC_ACCEPTED = metrics_lib.counter(
    'skytpu_engine_spec_accepted_tokens_total',
    'Draft tokens accepted by speculative verify ticks (the emitted '
    'base token per tick is not counted).')
_M_SPEC_ACCEPT_LEN = metrics_lib.histogram(
    'skytpu_engine_spec_accept_len_tokens',
    'Tokens emitted per slot per speculative verify tick (1 = every '
    'draft rejected; k+1 = all accepted plus the bonus token).',
    buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))
_M_KERNEL_PALLAS = metrics_lib.gauge(
    'skytpu_engine_decode_kernel_pallas',
    'Whether the paged decode attention runs the Pallas kernel '
    '(1) or the jnp gather fallback (0); absent-dense engines set 0.')


def _maybe_page_journal():
    """Journal page alloc/free events only when someone is watching:
    the `serve.page_pool` chaos site is armed (scenarios replay the
    journal to prove alloc/free balance) or SKYTPU_SERVE_PAGE_EVENTS
    is set.  Production admissions stay I/O-free."""
    from skypilot_tpu.chaos import injector as chaos_injector  # pylint: disable=import-outside-toplevel
    if not (os.environ.get('SKYTPU_SERVE_PAGE_EVENTS') or
            chaos_injector.site_armed('serve.page_pool')):
        return None
    from skypilot_tpu.observability import events as events_lib  # pylint: disable=import-outside-toplevel
    return events_lib.get_journal(
        os.path.join(events_lib.journal_root(), 'serve.jsonl'))


class ContinuousBatchingEngine:
    """Submit() from any thread; one worker thread owns the device."""

    def __init__(self, cfg, params, *, max_len: int = 512,
                 slots: int = 4, prefill_chunk: int = 512,
                 max_queue: int = 0,
                 queue_ttl: Optional[float] = None,
                 max_top_k: int = 64, max_stop_ids: int = 16,
                 pipelined: bool = True, mesh=None,
                 kv_pages: Optional[int] = None, page_size: int = 16,
                 quantize_kv: bool = False,
                 prefix_caching: bool = True,
                 spec_tokens: int = 0) -> None:
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.models import decode
        from skypilot_tpu.ops import paged_attention as paged_attention_lib

        self.cfg = cfg
        self.params = params
        # Live weight swap (POST /weights_swap): bumped by swap_params()
        # ON THE WORKER THREAD between ticks; read anywhere (int loads
        # are atomic under the GIL).  Epoch 0 = the params the engine
        # booted with.
        self._weight_epoch = 0
        self.max_len = max_len
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.max_queue = int(max_queue)          # 0 = unbounded
        self.queue_ttl = queue_ttl               # None = no expiry
        self.max_top_k = int(max_top_k)
        self.max_stop_ids = int(max_stop_ids)
        self.pipelined = pipelined
        self._jnp = jnp
        self._jax = jax
        self._slots = [scheduler.Slot() for _ in range(slots)]
        self._queue = scheduler.AdmissionQueue(
            max_queue=max_queue, queue_ttl=queue_ttl,
            drain_estimate=self._drain_estimate)
        self._cond = self._queue.cond
        self._stop = threading.Event()
        self._sampler = sampler_lib.SlotSampler(self.max_top_k,
                                                self.max_stop_ids)
        self.quantize_kv = bool(quantize_kv)
        # Host ops the worker runs between ticks (KV handoff imports
        # mutate self._cache, which only the worker may touch); each
        # entry is a no-raise closure that reports through its own
        # result holder.
        self._host_ops: Deque[Any] = collections.deque()
        self._host_ops_lock = threading.Lock()
        # Exports materialize a private prefill cache each; bound the
        # concurrent ones so a handoff stampede can't blow memory.
        self._export_sem = threading.BoundedSemaphore(2)

        self.spec_tokens = int(spec_tokens)
        if self.spec_tokens < 0:
            raise ValueError(
                f'spec_tokens must be >= 0, got {spec_tokens}')
        self._kv: Optional[cache_manager.PagedKVManager] = None
        if kv_pages is not None:
            if not pipelined:
                raise ValueError('kv_pages (paged KV cache) requires '
                                 'the pipelined engine')
            if max_len % page_size:
                raise ValueError(
                    f'max_len {max_len} must be a multiple of '
                    f'page_size {page_size} (private prefill caches '
                    f'scatter whole pages into the pool)')
            self._kv = cache_manager.PagedKVManager(
                int(kv_pages), int(page_size), slots,
                prefix_caching=prefix_caching,
                journal=_maybe_page_journal())
            self._cache = decode.init_paged_cache(
                cfg, int(kv_pages), int(page_size), slots,
                max_len // int(page_size), quantize_kv=quantize_kv)
        else:
            if self.spec_tokens:
                raise ValueError(
                    'spec_tokens (speculative decoding) requires the '
                    'paged KV engine (kv_pages): rejected drafts roll '
                    'back through the pool\'s reserved null page')
            self._cache = decode.init_slot_cache(cfg, slots, max_len)
        # Which attention path the paged tick runs — resolved ONCE here
        # (env SKYTPU_DECODE_KERNEL, defaulting to the Pallas kernel
        # wherever it can run) and baked into the jitted partials below
        # as a closure constant, so the hot loop never re-reads the
        # environment.
        self.decode_kernel = (
            paged_attention_lib.decode_kernel_choice()
            if self._kv is not None else 'dense')
        _M_KERNEL_PALLAS.set(
            1 if self.decode_kernel == 'pallas' else 0)
        self._state = decode.init_engine_state(slots, max_stop_ids)
        self._mesh = mesh
        if mesh is not None:
            # Tensor-sharded serving: place the KV pool and the tiny
            # per-slot state explicitly (kv_heads on 'tensor', state
            # replicated) instead of leaving GSPMD to guess from the
            # first donated step.
            from skypilot_tpu.parallel import sharding as sharding_lib
            if self._kv is not None:
                self._cache = jax.device_put(
                    self._cache, sharding_lib.paged_cache_sharding(
                        mesh, quantized=quantize_kv))
            else:
                # Per-leaf shardings: the rank-5 kv spec must not be
                # broadcast onto the rank-1 lengths leaf.
                kv_sharding = sharding_lib.slot_cache_sharding(mesh)
                self._cache = jax.device_put(
                    self._cache,
                    {'k': kv_sharding, 'v': kv_sharding,
                     'lengths': sharding_lib.replicated(mesh)})
            self._state = jax.device_put(
                self._state, sharding_lib.engine_state_sharding(mesh))
        self._tokens = jnp.zeros((slots, 1), jnp.int32)  # legacy loop

        if self._kv is not None:
            self._step = jax.jit(
                functools.partial(decode.paged_engine_step, cfg,
                                  max_top_k=self.max_top_k,
                                  kernel=self.decode_kernel),
                donate_argnums=(2,))
            # Speculative verify tick: same donated-pool discipline as
            # the plain tick, plus the [slots, k] draft batch; the
            # kernel choice is a closure constant, so both ticks hit
            # the same attention path.
            self._spec_step = jax.jit(
                functools.partial(decode.paged_spec_engine_step, cfg,
                                  max_top_k=self.max_top_k,
                                  kernel=self.decode_kernel),
                donate_argnums=(2,))
            # Block-table surgery: donated so XLA patches the pool's
            # tiny int32 tables in place.
            self._admit_paged = jax.jit(decode.paged_admit_slot,
                                        donate_argnums=(0,))
            self._release_paged = jax.jit(decode.paged_release_slot,
                                          donate_argnums=(0,))
            # Private-prefill -> pool page scatter (quantizing when the
            # pool is int8); the pool is donated (in-place patch), the
            # private cache is not (its [L,1,h,T,d] layout cannot alias
            # the page-major pool output — donating it just warns).
            self._insert_pages = jax.jit(
                decode.insert_prefill_pages,
                static_argnames=('first_page',), donate_argnums=(0,))
            # Prefix-hit seeding: cached pages -> the leading positions
            # of a fresh private cache (pool read-only, NOT donated).
            self._seed_private = jax.jit(
                functools.partial(decode.paged_seed_private, cfg),
                static_argnames=('priv_len',))
            # KV handoff adoption: imported page contents -> pool pages
            # (quantizing when the pool is int8); pool donated.  The
            # quantized variant lands int8 wire bytes verbatim — the
            # import path's hot case never dequantizes.
            self._write_pages = jax.jit(decode.write_pages,
                                        donate_argnums=(0,))
            self._write_pages_q = jax.jit(decode.write_pages_quantized,
                                          donate_argnums=(0,))
        else:
            self._step = jax.jit(
                functools.partial(decode.engine_step, cfg,
                                  max_top_k=self.max_top_k),
                donate_argnums=(2,))
        self._legacy_step = jax.jit(
            lambda p, t, c: decode.batched_step(cfg, p, t, c),
            donate_argnums=(2,))
        # Jitted prefill: one compile per prompt-length bucket (the
        # whole point of the bucket padding), not eager per-op dispatch
        # per admission.
        self._prefill = jax.jit(
            lambda params, toks: decode.prefill(cfg, params, toks,
                                                max_len=max_len))
        # Chunk continuation at index > 0 (masked per-position causal
        # path): one compile per chunk width; the private prefill cache
        # is donated so XLA extends it in place.
        self._prefill_chunk = jax.jit(
            lambda params, toks, cache: decode.prefill_chunk(
                cfg, params, toks, cache),
            donate_argnums=(2,))
        # Jitted in-place slot adoption (dense): eager
        # dynamic_update_slice would materialize two full copies of the
        # pool cache per admission; donation lets XLA update in place.
        self._insert = jax.jit(decode.insert_prefill,
                               donate_argnums=(0,))
        # ---- continuous profiling plane (observability/profiling.py).
        # Tick-phase ring + recompile sentinel; both collapse to no-ops
        # under SKYTPU_PROFILE_DISABLE.  Every resolved jit entry above
        # (incl. the Pallas kernel path, a closure constant of _step)
        # gets the sentinel's O(1) cache-size probe so a steady-state
        # recompile is counted and journaled instead of silently
        # stalling ticks.
        self._profiler = profiling.TickProfiler()
        self._sentinel = profiling.RecompileSentinel()
        for attr in ('_step', '_spec_step', '_admit_paged',
                     '_release_paged', '_insert_pages', '_seed_private',
                     '_write_pages', '_write_pages_q', '_legacy_step',
                     '_prefill', '_prefill_chunk', '_insert'):
            entry = getattr(self, attr, None)
            if entry is not None:
                setattr(self, attr,
                        self._sentinel.wrap(attr.lstrip('_'), entry))
        self._failed: Optional[Exception] = None

        # ---- metrics (updated under _metrics_lock; read by stats()).
        # These are the per-ENGINE view; every update is mirrored into
        # the process-global registry instruments above (what
        # GET /metrics exposes).
        self._metrics_lock = threading.Lock()
        self._tokens_generated = 0
        self._ticks = 0
        self._prefill_chunks = 0
        self._page_deferrals = 0
        self._spec_ticks = 0
        self._spec_slot_ticks = 0   # (live slot, verify tick) pairs
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._rate_window: Deque[Tuple[float, int]] = collections.deque()
        # Finished per-request spans (queue/prefill/TTFT/ITL/total),
        # bounded; surfaced via stats()['recent_spans'] and span().
        self._spans = tracing.SpanStore()
        _M_SLOTS.set(slots)
        _M_BUSY_SLOTS.set(0)

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ public

    def submit(self, prompt_ids: List[int], max_new_tokens: int,
               stop_token=None, sampling=None,
               request_id: Optional[str] = None,
               route_meta: Optional[Dict[str, Any]] = None,
               deadline_ms: Optional[float] = None,
               qos_class: Optional[str] = None
               ) -> scheduler.Request:
        """stop_token: None, one id, or an iterable of ids — the
        request finishes at the FIRST generated member of the set
        (multi-EOS: model-level EOS + chat turn-end markers).

        sampling: optional models.decode.SamplingConfig.  temperature
        <= 0 decodes greedily (the deterministic serving default);
        temperature > 0 samples on device with per-request top_k/seed —
        deterministic for a given seed (the slot's key chain splits
        once per generated token, independent of other traffic).

        request_id: the propagated X-SkyTPU-Request-Id (generated when
        absent); names the request's span record and timeline events.

        deadline_ms: total time budget from submission (the propagated
        X-SkyTPU-Deadline-Ms).  Queued past it -> DeadlineExceeded at
        pop; mid-decode past it -> the worker reaps the slot and frees
        its KV pages on the next tick.

        qos_class: the propagated X-SkyTPU-QoS-Class.  The scheduler
        clamps max_new_tokens to the class token budget, applies the
        class deadline default when deadline_ms is None, and pops
        queued work in smooth-weighted class order."""
        if not prompt_ids:
            raise ValueError('empty prompt')
        if max_new_tokens < 1:
            raise ValueError(
                f'max_new_tokens must be >= 1, got {max_new_tokens}')
        if len(prompt_ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f'prompt {len(prompt_ids)} + new {max_new_tokens} '
                f'exceeds max_len {self.max_len}')
        temperature, top_k, seed = sampler_lib.validate_sampling(
            sampling, max_top_k=self.max_top_k,
            pipelined=self.pipelined)
        request = scheduler.Request(prompt_ids, max_new_tokens,
                                    stop_token, temperature=temperature,
                                    top_k=top_k, seed=seed,
                                    request_id=request_id,
                                    route_meta=route_meta,
                                    deadline_ms=deadline_ms,
                                    qos_class=qos_class)
        request._span_store = self._spans  # pylint: disable=protected-access
        # The epoch in force AT SUBMIT: a swap landing mid-decode still
        # attributes this request to the weights that prefilled it.
        request.span.weight_epoch = self._weight_epoch
        sampler_lib.validate_stop_ids(request.stop_ids,
                                      self.max_stop_ids)
        if self._stop.is_set() or self._failed is not None:
            raise RuntimeError('batching engine is stopped'
                               if self._failed is None else
                               f'batching engine failed: {self._failed}')
        if self._kv is not None:
            # Admission is page-aware: a request that could NEVER fit
            # is a caller error; a pool too busy RIGHT NOW while a
            # backlog already waits is backpressure (429 + Retry-After)
            # — the honest degraded mode for an exhausted pool.
            need = self._kv.pages_needed(len(prompt_ids),
                                         max_new_tokens)
            if need > self._kv.pool.capacity:
                raise ValueError(
                    f'request needs {need} KV pages > pool capacity '
                    f'{self._kv.pool.capacity} (pool of '
                    f'{self._kv.pool.capacity} pages x '
                    f'{self._kv.page_size} tokens)')
            if len(self._queue) > 0 and not self._kv.can_admit(need):
                raise self._queue.reject(
                    'pages_exhausted',
                    f'KV page pool exhausted ({need} page(s) needed, '
                    f'{self._kv.pool.free_count} free); retry later')
        self._queue.submit(request)
        if self._stop.is_set():
            # Lost the race with stop(): its drain may have already run,
            # so fail this request directly (idempotent via the event).
            if not request.done.is_set():
                request._finish(  # pylint: disable=protected-access
                    RuntimeError('batching engine stopped'))
        return request

    def generate(self, prompt_ids: List[int], max_new_tokens: int,
                 stop_token=None, sampling=None,
                 timeout: float = 600.0) -> List[int]:
        return self.submit(prompt_ids, max_new_tokens, stop_token,
                           sampling=sampling).result(timeout)

    # ------------------------------------------------------- KV handoff

    def export_prefill(self, prompt_ids: List[int],
                       page_size: Optional[int] = None,
                       binary: bool = False) -> Any:
        """Prefill a prompt and export its FULL KV pages for another
        replica to adopt (the prefill side of a disaggregated handoff).

        Runs the same chunked-prefill path an admission would, but into
        a private cache that never touches this engine's slot pool or
        page pool — a prefill replica can export for many decode
        replicas without competing with its own admissions.  Returns
        the serve/handoff.py wire payload: the prompt's full pages in
        page-major layout (int8 + scales when this engine quantizes
        KV), plus the chain hashes the importer registers them under.
        The sub-page tail of the prompt is the importer's to prefill
        (it is < one page and rides the normal partial-prefix path).

        binary=True returns the `application/octet-stream` frame
        (handoff.encode_binary) instead of the JSON/base64 dict — same
        fields, raw array bytes, ~25% less on the wire.
        """
        import numpy as np  # pylint: disable=import-outside-toplevel

        from skypilot_tpu.models import decode  # pylint: disable=import-outside-toplevel
        if self.cfg.n_experts > 0:
            raise HandoffError(
                'MoE prefill couples every prompt token through the '
                'capacity dispatch; its KV cannot transfer page-wise')
        if self._stop.is_set() or self._failed is not None:
            raise RuntimeError('batching engine is stopped'
                               if self._failed is None else
                               f'batching engine failed: {self._failed}')
        ps = int(page_size) if page_size else (
            self._kv.page_size if self._kv is not None else 16)
        n = len(prompt_ids)
        if n < 2:
            raise HandoffError('prompt too short to export')
        if n > self.max_len:
            raise HandoffError(
                f'prompt {n} exceeds this replica\'s max_len '
                f'{self.max_len}')
        full = (n - 1) // ps     # full pages inside the prefilled [0, n-1)
        if full < 1:
            raise HandoffError(
                f'prompt {n} holds no full {ps}-token page to export')
        hashes = cache_manager.chunk_hashes(prompt_ids[:n - 1], ps)
        n_target = n - 1
        encode = (handoff_lib.encode_binary if binary
                  else handoff_lib.encode_payload)
        with self._export_sem:
            cache = self._prefill_private(prompt_ids, n_target)
            if self.quantize_kv:
                kq, vq, ks, vs = decode.export_private_pages(
                    cache, full, ps, quantize=True)
                payload = encode(
                    hashes[:full], ps, np.asarray(kq), np.asarray(vq),
                    np.asarray(ks), np.asarray(vs))
            else:
                k, v = decode.export_private_pages(cache, full, ps)
                payload = encode(
                    hashes[:full], ps, np.asarray(k), np.asarray(v))
        _M_HANDOFF_EXPORTS.inc()
        return payload

    def _prefill_private(self, prompt_ids: List[int],
                         n_target: int) -> Dict[str, Any]:
        """Prefill tokens [0, n_target) into a FRESH private cache
        ([L, 1, h_kv, max_len, d]) without touching the slot pool:
        chunk 0 through the bucketed flash path, then masked chunk
        continuations — the same compile cache the admission path
        uses.  The slice engine overrides this with a one-shot
        sequence-parallel prefill for long prompts."""
        import numpy as np  # pylint: disable=import-outside-toplevel
        jnp = self._jnp
        chunk = self.prefill_chunk
        take = min(n_target, chunk)
        bucket = min(self._bucket(take), self.max_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :take] = prompt_ids[:take]
        _, cache = self._prefill(self.params, jnp.asarray(padded))
        cache = dict(cache, index=jnp.asarray(take, jnp.int32))
        consumed = take
        while consumed < n_target:
            take = min(n_target - consumed, chunk)
            width = min(self._bucket(take), chunk,
                        self.max_len - consumed)
            piece = np.zeros((1, width), np.int32)
            piece[0, :take] = prompt_ids[consumed:consumed + take]
            _, cache = self._prefill_chunk(self.params,
                                           jnp.asarray(piece), cache)
            cache = dict(cache,
                         index=jnp.asarray(consumed + take, jnp.int32))
            consumed += take
        return cache

    def import_pages(self, hashes: List[int], page_size: int,
                     k_pages, v_pages, k_scale=None,
                     v_scale=None) -> Tuple[int, int]:
        """Adopt exported KV pages into this engine's pool + prefix
        cache (the decode side of a handoff).  Returns
        (pages_imported, pages_already_cached).

        The pages are published exactly like locally prefilled ones:
        registered in the prefix cache under their chain hashes, so
        the follow-up submit() adopts them as a prefix hit (and so do
        later requests sharing the prompt).  Pool exhaustion raises
        QueueFull (reason pages_exhausted -> HTTP 429 + Retry-After);
        any structural mismatch raises HandoffError — the router falls
        back to local prefill, the request is never lost.
        """
        import numpy as np  # pylint: disable=import-outside-toplevel
        from skypilot_tpu.chaos import injector  # pylint: disable=import-outside-toplevel
        if self._kv is None:
            raise HandoffError('KV import needs a paged engine '
                               '(--kv-pages)')
        if not self._kv.prefix_caching:
            raise HandoffError('KV import needs the prefix cache '
                               '(imports publish pages through it)')
        if self.cfg.n_experts > 0:
            raise HandoffError('MoE engines do not reuse prefix pages')
        if int(page_size) != self._kv.page_size:
            raise HandoffError(
                f'page_size mismatch: payload {page_size}, '
                f'pool {self._kv.page_size}')
        if len(hashes) > self._kv.pool.capacity:
            raise HandoffError(
                f'{len(hashes)} pages exceed pool capacity '
                f'{self._kv.pool.capacity}')
        if (getattr(k_pages, 'dtype', None) is not None and
                str(k_pages.dtype) == 'int8' and k_scale is None):
            raise HandoffError('int8 pages need their scales')
        # Chaos: deny -> the decode replica refuses the handoff (the
        # router must fall back to local prefill); delay -> handoff
        # latency (runs on the HTTP thread, never stalls the ticks).
        if injector.inject('serve.kv_handoff',
                           pages=len(hashes)) is injector.DENY:
            _M_HANDOFF_IMPORTS.labels(result='denied').inc()
            raise HandoffRejected(
                'chaos: KV handoff import denied')
        if self._stop.is_set() or self._failed is not None:
            raise RuntimeError('batching engine is stopped'
                               if self._failed is None else
                               f'batching engine failed: {self._failed}')
        holder: Dict[str, Any] = {}
        done = threading.Event()

        def op() -> None:
            # Runs ON THE WORKER THREAD: self._cache and the prefix
            # cache are worker-owned; every outcome lands in `holder`.
            try:
                if self._stop.is_set():
                    raise RuntimeError('batching engine stopped')
                cached = self._kv.import_prefix_depth(hashes)
                fresh_hashes = hashes[cached:]
                if not fresh_hashes:
                    holder['result'] = (0, cached)
                    return
                fresh = self._kv.alloc_pages(len(fresh_hashes))
                try:
                    jnp = self._jnp
                    ids = np.asarray(fresh, np.int32)
                    if k_scale is not None and self.quantize_kv:
                        # int8 wire -> int8 pool: scatter q/scale
                        # verbatim (no dequant/requant on the decode
                        # replica's critical path).
                        self._cache = self._write_pages_q(
                            self._cache,
                            jnp.asarray(k_pages[:, cached:]),
                            jnp.asarray(v_pages[:, cached:]),
                            jnp.asarray(k_scale[:, cached:]),
                            jnp.asarray(v_scale[:, cached:]), ids)
                    elif k_scale is not None:
                        # int8 wire -> float pool: dequantize once.
                        self._cache = self._write_pages(
                            self._cache,
                            jnp.asarray(
                                k_pages[:, cached:].astype(np.float32)
                                * k_scale[:, cached:, ..., None]),
                            jnp.asarray(
                                v_pages[:, cached:].astype(np.float32)
                                * v_scale[:, cached:, ..., None]),
                            ids)
                    else:
                        self._cache = self._write_pages(
                            self._cache,
                            jnp.asarray(k_pages[:, cached:]),
                            jnp.asarray(v_pages[:, cached:]), ids)
                    self._kv.prefix.register(fresh_hashes, fresh)
                finally:
                    # register() pinned the published pages; dropping
                    # the import's alloc ref leaves them pin-held (and
                    # frees them outright if anything above raised).
                    self._kv.pool.decref(fresh)
                holder['result'] = (len(fresh_hashes), cached)
            except BaseException as e:  # pylint: disable=broad-except
                holder['error'] = e
            finally:
                done.set()

        with self._host_ops_lock:
            self._host_ops.append(op)
        with self._cond:
            self._cond.notify_all()
        if not done.wait(timeout=60):
            _M_HANDOFF_IMPORTS.labels(result='timeout').inc()
            raise HandoffError('KV import timed out waiting for the '
                               'engine worker')
        if 'error' in holder:
            error = holder['error']
            if isinstance(error, cache_manager.PagesExhausted):
                _M_HANDOFF_IMPORTS.labels(
                    result='pages_exhausted').inc()
                raise self._queue.reject(
                    'pages_exhausted',
                    f'KV page pool exhausted for handoff import '
                    f'({len(hashes)} page(s) needed); retry later')
            _M_HANDOFF_IMPORTS.labels(result='error').inc()
            raise error
        _M_HANDOFF_IMPORTS.labels(result='ok').inc()
        return holder['result']

    def export_prefix_pages(self, max_pages: int = 64,
                            binary: bool = True) -> Any:
        """Export the hottest prefix-cache pages as a handoff payload
        (the drain-time sibling handoff: a retiring replica ships its
        still-pinned session prefixes to a same-role survivor so those
        sessions don't cold-start).  Unlike export_prefill this reads
        the POOL pages the prefix cache pins — no prefill runs.

        Returns the binary octet-stream frame (binary=True) or the
        JSON/base64 dict; raises HandoffError when this engine has no
        exportable prefixes (dense cache, prefix caching off, empty
        cache)."""
        import numpy as np  # pylint: disable=import-outside-toplevel
        if self._kv is None:
            raise HandoffError('prefix export needs a paged engine '
                               '(--kv-pages)')
        if not self._kv.prefix_caching:
            raise HandoffError('prefix export needs the prefix cache')
        if self._stop.is_set() or self._failed is not None:
            raise RuntimeError('batching engine is stopped'
                               if self._failed is None else
                               f'batching engine failed: {self._failed}')
        holder: Dict[str, Any] = {}
        done = threading.Event()
        encode = (handoff_lib.encode_binary if binary
                  else handoff_lib.encode_payload)

        def op() -> None:
            # Worker thread: the pool cache and prefix cache are
            # worker-owned; the gather below reads pages no tick
            # mutates (full prefix pages are immutable once written).
            try:
                if self._stop.is_set():
                    raise RuntimeError('batching engine stopped')
                entries = self._kv.prefix.hot_entries(int(max_pages))
                if not entries:
                    raise HandoffError('no cached prefixes to export')
                hashes = [h for h, _ in entries]
                ids = np.asarray([p for _, p in entries], np.int32)
                k = self._cache['k']
                v = self._cache['v']
                if self.quantize_kv:
                    payload = encode(
                        hashes, self._kv.page_size,
                        np.asarray(k['q'][:, ids]),
                        np.asarray(v['q'][:, ids]),
                        np.asarray(k['scale'][:, ids]),
                        np.asarray(v['scale'][:, ids]))
                else:
                    payload = encode(
                        hashes, self._kv.page_size,
                        np.asarray(k[:, ids], np.float32),
                        np.asarray(v[:, ids], np.float32))
                holder['result'] = payload
            except BaseException as e:  # pylint: disable=broad-except
                holder['error'] = e
            finally:
                done.set()

        with self._host_ops_lock:
            self._host_ops.append(op)
        with self._cond:
            self._cond.notify_all()
        if not done.wait(timeout=60):
            raise HandoffError('prefix export timed out waiting for '
                               'the engine worker')
        if 'error' in holder:
            raise holder['error']
        _M_HANDOFF_EXPORTS.inc()
        return holder['result']

    def swap_params(self, new_params) -> int:
        """Swap the serving weights in place WITHOUT dropping the KV
        page pool or any in-flight request — the live half of
        `POST /weights_swap`.

        Runs as a host op ON THE WORKER THREAD between ticks: that IS
        the scoped tick pause — no tick can be mid-flight while
        self.params is reassigned, and the jitted steps take params as
        an argument (never donated), so the next tick simply decodes
        with the new weights against the same cache.  In-flight
        requests keep their KV pages; requests submitted after the
        swap are span-stamped with the new epoch.  Returns the new
        weight epoch.

        Callers are responsible for device placement (the server
        restores the checkpoint with the engine's shardings before
        calling); this method only performs the epoch-ordered
        assignment."""
        if self._stop.is_set() or self._failed is not None:
            raise RuntimeError('batching engine is stopped'
                               if self._failed is None else
                               f'batching engine failed: {self._failed}')
        holder: Dict[str, Any] = {}
        done = threading.Event()

        def op() -> None:
            # Worker thread: between ticks by construction.
            try:
                if self._stop.is_set():
                    raise RuntimeError('batching engine stopped')
                self.params = new_params
                self._weight_epoch += 1
                holder['result'] = self._weight_epoch
            except BaseException as e:  # pylint: disable=broad-except
                holder['error'] = e
            finally:
                done.set()

        with self._host_ops_lock:
            self._host_ops.append(op)
        with self._cond:
            self._cond.notify_all()
        if not done.wait(timeout=60):
            raise RuntimeError('weight swap timed out waiting for the '
                               'engine worker')
        if 'error' in holder:
            raise holder['error']
        return holder['result']

    @property
    def weight_epoch(self) -> int:
        return self._weight_epoch

    def _drain_host_ops(self) -> int:
        ran = 0
        while True:
            with self._host_ops_lock:
                if not self._host_ops:
                    return ran
                op = self._host_ops.popleft()
            op()   # no-raise by construction
            ran += 1

    def _drain_estimate(self) -> float:
        """Rough seconds until one queue position frees: backlog size
        over the recent decode rate (floor 1s — it feeds Retry-After)."""
        rate = self._decode_rate()
        if rate <= 0:
            return 1.0
        avg_new = 32.0  # no per-request oracle; a slot's typical budget
        return max(1.0, len(self._queue) * avg_new /
                   (rate * max(1, len(self._slots))))

    def _decode_rate(self) -> float:
        with self._metrics_lock:
            if not self._rate_window:
                return 0.0
            t0 = self._rate_window[0][0]
            span = time.monotonic() - t0
            total = sum(n for _, n in self._rate_window)
        return total / max(span, 1e-3)

    def stats(self) -> Dict[str, Any]:
        """Live scheduling + decode-saturation stats (surfaced via the
        server's /health): queue depth and slot occupancy are the
        scale-out signals, decode_tokens_per_s and the queue-wait
        histogram say whether the replica is decode-bound rather than
        merely popular (serve/autoscalers.py consumes busy/slots as
        replica load).  Paged engines add the page-pool view:
        kv_pages_{total,used,free,pinned}, prefix-cache entry/hit/miss
        counts, and pages_exhausted_deferrals."""
        busy = sum(1 for s in self._slots if s.active)
        with self._metrics_lock:
            stats = {
                'slots': len(self._slots),
                'busy_slots': busy,
                'tokens_generated': self._tokens_generated,
                'failed': self._failed is not None,
                'ticks': self._ticks,
                'prefill_chunks': self._prefill_chunks,
                'prefill_chunk': self.prefill_chunk,
                'pipelined': self.pipelined,
                'paged': self._kv is not None,
                'decode_kernel': self.decode_kernel,
                'spec_tokens': self.spec_tokens,
                'weight_epoch': self._weight_epoch,
            }
            if self.spec_tokens:
                stats['spec_ticks'] = self._spec_ticks
                stats['spec_proposed_tokens'] = self._spec_proposed
                stats['spec_accepted_tokens'] = self._spec_accepted
                # Mean tokens per slot per verify tick: accepted
                # drafts plus the always-emitted verified base token.
                stats['spec_accept_len_mean'] = (
                    round((self._spec_accepted +
                           self._spec_slot_ticks) /
                          self._spec_slot_ticks, 3)
                    if self._spec_slot_ticks else None)
        stats.update(self._queue.stats())
        if self._kv is not None:
            stats.update(self._kv.stats())
            with self._metrics_lock:
                stats['pages_exhausted_deferrals'] = self._page_deferrals
        rate = round(self._decode_rate(), 3)
        stats['decode_tokens_per_s'] = rate
        # Per-request phase traces (newest first) — the "why was THIS
        # request slow" answer, keyed by X-SkyTPU-Request-Id.
        stats['recent_spans'] = self._spans.recent()
        # Freshen the scrape-time gauges so /metrics agrees with
        # /health no matter which is polled.
        _M_SLOTS.set(stats['slots'])
        _M_BUSY_SLOTS.set(busy)
        _M_DECODE_RATE.set(rate)
        return stats

    def span(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The finished span record for a request id (None while the
        request is still running or once it aged out of the store)."""
        return self._spans.get(request_id)

    def profile(self) -> Dict[str, Any]:
        """Continuous-profiling snapshot (what `GET /profile` serves):
        the tick-phase ring with per-phase quantiles, device-memory
        watermarks, the profiler's modeled self-overhead, and the
        recompile sentinel's per-jit-entry compile counts."""
        snap = self._profiler.snapshot()
        snap['recompiles'] = self._sentinel.snapshot()
        snap['pipelined'] = self.pipelined
        return snap

    def set_role_budget(
            self, budget: Optional[scheduler.RoleBudget]) -> bool:
        """Swap the fractional-role budget in place — warm weights and
        page pool untouched; the next tick's admission gate and prefill
        chunk clamp pick it up.  Version-ordered: a stale push (lower
        version than the one in force) is dropped and False returned.
        None removes the clamp entirely."""
        return self._queue.set_role_budget(budget)

    @property
    def role_budget(self) -> Optional[scheduler.RoleBudget]:
        return self._queue.role_budget

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self._thread.join(timeout=10)
        # Fail fast for anything still queued or in flight — callers
        # must not sit out their full result() timeout at shutdown.
        self._queue.drain(
            lambda: RuntimeError('batching engine stopped'))
        for slot in self._slots:
            if slot.request is not None:
                slot.request._finish(  # pylint: disable=protected-access
                    RuntimeError('batching engine stopped'))
                slot.request = None
            slot.drafter = None
        if self._kv is not None:
            # Host-side accounting only (the device is going away):
            # every slot- and prefix-held page returns to the pool, so
            # the alloc/free journal balances.
            self._kv.release_all()
        # Handoff imports still queued never ran; unblock their waiters.
        self._drain_host_ops()

    # ------------------------------------------------------------ metrics

    def _record_tokens(self, n: int) -> None:
        now = time.monotonic()
        with self._metrics_lock:
            self._tokens_generated += n
            self._rate_window.append((now, n))
            while (self._rate_window and
                   now - self._rate_window[0][0] > 10.0):
                self._rate_window.popleft()
        _M_TOKENS.inc(n)
        _M_DECODE_RATE.set(round(self._decode_rate(), 3))

    def _record_chunk(self) -> None:
        _M_PREFILL_CHUNKS.inc()
        with self._metrics_lock:
            self._prefill_chunks += 1

    # ------------------------------------------------------------ worker

    def _bucket(self, n: int) -> int:
        for b in _PREFILL_BUCKETS:
            if n <= b:
                return b
        return n

    # ----------------------------------------------- pipelined admission

    def _plan_pages(self, request: scheduler.Request
                    ) -> Optional[cache_manager.AdmissionPlan]:
        """Paged mode: match the prefix cache and allocate this
        request's pages (raises PagesExhausted -> caller defers)."""
        if self._kv is None:
            return None
        # MoE prefill couples every prompt token through the capacity
        # dispatch, so a shared prefix does NOT have shared KV — pages
        # pool, but never cross-request reuse.
        plan = self._kv.plan_admission(
            request.prompt_ids, request.max_new_tokens,
            prefix_ok=(self.cfg.n_experts == 0))
        request.span.prefix_hit_pages = plan.prefix_hit_pages
        return plan

    def _pad_row(self, row: List[int]):
        import numpy as np  # pylint: disable=import-outside-toplevel
        padded = np.zeros((self.max_len // self._kv.page_size,),
                          np.int32)
        padded[:len(row)] = row
        return self._jnp.asarray(padded)

    def _start_admission(self, slot_id: int,
                         request: scheduler.Request
                         ) -> Optional[scheduler.PendingPrefill]:
        """Begin admitting `request` into `slot_id`.  Returns a
        PendingPrefill when chunks remain, None when the slot is live
        (or the request finished at admission).  Raises PagesExhausted
        (pool backpressure) BEFORE touching any state — the caller
        requeues the request at the head."""
        jnp = self._jnp
        slot = self._slots[slot_id]
        prompt = request.prompt_ids
        n = len(prompt)
        plan = self._plan_pages(request)   # may raise PagesExhausted
        if plan is not None:
            self._kv.commit(slot_id, plan)
        self._queue.record_admission(request)
        if self.cfg.n_experts > 0 and n > 0:
            # MoE: the capacity dispatch couples EVERY prompt token, so
            # pad tokens, an n-1/last-token split, and chunk boundaries
            # would all change which tokens drop — only a full-prompt
            # unpadded prefill matches the single-sequence reference.
            # The first generated token therefore comes from the
            # prefill logits (one compile per distinct MoE prompt
            # length), selected with the same key chain a tick uses.
            t_prefill = time.perf_counter()
            logits, pre = self._prefill(
                self.params, jnp.asarray([prompt], jnp.int32))
            request.span.mark_prefill_chunk(
                time.perf_counter() - t_prefill)
            if plan is not None:
                import numpy as np  # pylint: disable=import-outside-toplevel
                n_pages = -(-n // self._kv.page_size)
                self._cache = self._insert_pages(
                    self._cache, pre,
                    np.asarray(plan.row[:n_pages], np.int32),
                    first_page=0)
            else:
                self._cache = self._insert(self._cache, slot_id, pre, n)
            key = self._jax.random.PRNGKey(request.seed)
            carry, sub = self._jax.random.split(key)
            first = self._sampler.sample_one(logits, sub,
                                             request.temperature,
                                             request.top_k)
            request._push(first)  # pylint: disable=protected-access
            self._record_tokens(1)
            if (request.max_new_tokens <= 1 or
                    first in request.stop_ids):
                request._finish()  # pylint: disable=protected-access
                if plan is not None:
                    self._kv.release(slot_id)
                return None
            if plan is not None:
                self._cache = self._admit_paged(
                    self._cache, slot_id, self._pad_row(plan.row), n)
            slot.request = request
            self._activate(slot_id, request, first, n,
                           remaining=request.max_new_tokens - 1,
                           key=carry)
            return None
        if n <= 1:
            # Single-token prompt: empty slot; stale keys are masked
            # (per-position causal mask) and position 0 is overwritten
            # by the first step's write.
            if plan is not None:
                self._cache = self._admit_paged(
                    self._cache, slot_id, self._pad_row(plan.row), 0)
            else:
                self._cache = dict(
                    self._cache,
                    lengths=self._cache['lengths'].at[slot_id].set(0))
            slot.request = request
            self._activate(slot_id, request, int(prompt[-1]), 0,
                           remaining=request.max_new_tokens,
                           key=self._jax.random.PRNGKey(request.seed))
            return None
        if plan is not None and plan.n_reuse_tokens >= n - 1:
            # Full prefix hit (the prefilled region [0, n-1) is page-
            # aligned and entirely cached): no prefill at all — the
            # slot joins the next tick and TTFT collapses to one step.
            self._cache = self._admit_paged(
                self._cache, slot_id, self._pad_row(plan.row), n - 1)
            slot.request = request
            self._activate(slot_id, request, int(prompt[-1]), n - 1,
                           remaining=request.max_new_tokens,
                           key=self._jax.random.PRNGKey(request.seed))
            return None
        # Dense: prefill tokens [0, n-1) in chunks; the last REAL
        # prompt token is fed through the first batched step (it
        # overwrites the first pad position and attends only real
        # keys, so logits match unpadded decode exactly).
        slot.request = request
        pending = scheduler.PendingPrefill(slot_id, request, n - 1)
        pending.plan = plan
        return pending

    def _advance_prefill(self, pending: scheduler.PendingPrefill
                         ) -> bool:
        """Run ONE chunk of a pending prefill (this is the whole point:
        an admission stalls running decodes by at most one chunk).
        Returns True when the prefill completed and the slot went live.
        """
        jnp = self._jnp
        request = pending.request
        if request.cancelled or request.deadline_exceeded():
            if request.cancelled:
                request._finish()  # pylint: disable=protected-access
            else:
                _M_DEADLINE_REAPED.inc()
                request._finish(  # pylint: disable=protected-access
                    scheduler.DeadlineExceeded(
                        'request deadline passed mid-prefill'))
            self._slots[pending.slot_id].request = None
            if pending.plan is not None:
                self._release_slot_pages(pending.slot_id)
            return True  # pending is finished (slot freed)
        import numpy as np  # pylint: disable=import-outside-toplevel
        t_chunk0 = time.perf_counter()
        n_target = pending.n_target
        # Fractional-role clamp: a decode-heavy budget shrinks the
        # per-tick piece (floor 1 — prefill slows, never stalls).
        chunk = self._queue.prefill_tokens_per_tick(self.prefill_chunk)
        plan = pending.plan
        reuse_tokens = plan.n_reuse_tokens if plan is not None else 0
        if pending.cache is None and reuse_tokens > 0:
            # Prefix hit: seed the private cache from the cached pages
            # — positions [0, reuse_tokens) appear exactly as if they
            # had been prefilled here; only the tail chunks run.
            pending.cache = self._seed_private(
                self._cache,
                np.asarray(plan.reuse_pages, np.int32),
                priv_len=self.max_len)
            pending.consumed = reuse_tokens
            request.span.mark_prefill_chunk(
                time.perf_counter() - t_chunk0)
            return False
        if pending.cache is None:
            # Chunk 0: flash prefill from index 0 into a fresh private
            # cache.  Width = the bucket of min(n_target, chunk) so
            # short prompts keep today's bucket-bounded compile count;
            # pad keys land at positions >= the real length where the
            # causal mask hides them (and the first one is overwritten
            # by the real last token's step).  Padding is staged in
            # NUMPY: eager `.at[:n].set` would compile a tiny scatter
            # per distinct prompt length, right on the admission path.
            take = min(n_target, chunk)
            bucket = min(self._bucket(take), self.max_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :take] = request.prompt_ids[:take]
            _, pending.cache = self._prefill(self.params,
                                             jnp.asarray(padded))
            # The padded flash cache advanced index to `bucket`; chunk
            # continuations must write at the REAL consumed length.
            pending.cache = dict(pending.cache,
                                 index=jnp.asarray(take, jnp.int32))
            pending.consumed = take
        else:
            # Chunk i>0: masked per-position-causal continuation at
            # index = consumed.  Width is the POWER-OF-TWO BUCKET of
            # the remaining tail capped at `chunk` (bounded compile
            # count) AND at max_len - start: the write must fit the
            # private cache — a wider piece would make
            # dynamic_update_slice clamp its start index and silently
            # overwrite already-prefilled positions (reachable when
            # chunk does not divide max_len, and on every prefix-hit
            # seed whose tail is shorter than one chunk).  Pad
            # positions are beyond every real query's causal horizon
            # and each is overwritten by the decode step that reaches
            # it.
            start = pending.consumed
            take = min(n_target - start, chunk)
            width = min(self._bucket(take), chunk,
                        self.max_len - start)
            piece = np.zeros((1, width), np.int32)
            piece[0, :take] = request.prompt_ids[start:start + take]
            _, pending.cache = self._prefill_chunk(
                self.params, jnp.asarray(piece), pending.cache)
            pending.cache = dict(
                pending.cache,
                index=jnp.asarray(start + take, jnp.int32))
            pending.consumed = start + take
        request.span.mark_prefill_chunk(time.perf_counter() - t_chunk0)
        self._record_chunk()
        self._profiler.lap('prefill-chunk')
        if pending.consumed < n_target:
            return False
        return self._finish_prefill(pending)

    def _finish_prefill(self, pending: scheduler.PendingPrefill) -> bool:
        """All chunks in: adopt the private cache into the slot pool
        and join the next decode tick at length n-1 with the last REAL
        prompt token as input.  Split out of `_advance_prefill` so the
        slice engine's sequence-parallel prefill (one shot instead of
        chunks) lands through the same adoption path."""
        import numpy as np  # pylint: disable=import-outside-toplevel
        request = pending.request
        n_target = pending.n_target
        plan = pending.plan
        if plan is not None:
            # Scatter only the FRESH pages (the reused prefix already
            # lives in the pool — rewriting pages another slot shares,
            # even with identical values, is what this skips), then
            # point the block table at the full row and publish the
            # fresh full pages for the next prefix hit.
            ps = self._kv.page_size
            r = len(plan.reuse_pages)
            n_prompt_pages = -(-n_target // ps)
            self._cache = self._insert_pages(
                self._cache, pending.cache,
                np.asarray(plan.row[r:n_prompt_pages], np.int32),
                first_page=r)
            pending.cache = None   # donated to the scatter
            self._cache = self._admit_paged(
                self._cache, pending.slot_id,
                self._pad_row(plan.row), n_target)
            self._kv.register_prefix(plan)
        else:
            self._cache = self._insert(self._cache, pending.slot_id,
                                       pending.cache, n_target)
        self._activate(pending.slot_id, request,
                       int(request.prompt_ids[-1]), n_target,
                       remaining=request.max_new_tokens,
                       key=self._jax.random.PRNGKey(request.seed))
        # Cache adoption (page scatter / dense insert) + activation:
        # its own phase so prefill compute and pool surgery separate.
        self._profiler.lap('page-scatter')
        return True

    def _activate(self, slot_id: int, request: scheduler.Request,
                  token: int, length: int, *, remaining: int,
                  key) -> None:
        """Flip a slot live in the device state (one jitted dispatch)."""
        del length  # cache lengths are set by insert/admission paths
        if self.spec_tokens:
            # Seed the slot's drafter with everything decoded so far:
            # the history must END with the token the next tick feeds
            # (prompt[-1], or the MoE first-from-prefill token) so the
            # n-gram tail predicts continuations of it.
            self._slots[slot_id].drafter = sampler_lib.NgramDrafter(
                list(request.prompt_ids) + list(request.tokens))
        self._state = self._sampler.admit(
            self._state, slot_id, token, remaining, request.stop_ids,
            key, request.temperature, request.top_k)

    def _deactivate(self, slot_ids: List[int]) -> None:
        """Host-forced slot shutdown (cancel): flip active off so the
        next tick freezes the slot."""
        active = self._state['active']
        for i in slot_ids:
            active = active.at[i].set(False)
        self._state = dict(self._state, active=active)

    def _release_slot_pages(self, slot_id: int) -> None:
        """Paged mode: park the slot's block table on the null page
        (stale in-flight writes land in garbage, never in recycled
        pages), THEN return its pages to the pool."""
        if self._kv is None:
            return
        self._cache = self._release_paged(self._cache, slot_id)
        self._kv.release(slot_id)

    def _dispatch_step(self):
        """Dispatch one jitted engine tick.  The slice engine
        (serve/slice_replica.py) overrides this to broadcast the tick
        through its rank coordinator first — every host of a multi-host
        replica must dispatch the same SPMD step in lockstep."""
        return self._step(self.params, self._state, self._cache)

    def _dispatch_spec_step(self, drafts):
        """Dispatch one jitted speculative verify tick (the slice
        engine broadcasts it through its rank coordinator, exactly
        like `_dispatch_step`)."""
        return self._spec_step(self.params, self._state, self._cache,
                               drafts)

    def _spec_tick(self, live: Dict[int, scheduler.Request]) -> None:
        """One SYNCHRONOUS speculative tick: host drafters propose k
        tokens per live slot, ONE batched verify dispatch scores all of
        them against the paged cache, and each slot emits its longest
        exactly-matching prefix plus the verified bonus token.

        Spec mode gives up the one-deep tick pipeline on purpose: the
        drafter needs the tokens a tick just emitted before it can
        propose the next batch, so tick t+1's input depends on a host
        read of tick t.  What it buys back is up to k+1 tokens per
        dispatch — on repetitive text the dispatch count (the per-token
        floor on ITL) drops by the mean acceptance length.  Token
        streams are byte-identical to spec-off by construction: every
        emitted token is the engine's own verified choice, drafts only
        decide how many land per dispatch.
        """
        import numpy as np  # pylint: disable=import-outside-toplevel
        k = self.spec_tokens
        n_live = len(live)
        drafts = np.zeros((len(self._slots), k), np.int32)
        for slot_id in live:
            drafter = self._slots[slot_id].drafter
            if drafter is not None:
                drafts[slot_id] = drafter.propose(k)
        drafts_dev = self._jnp.asarray(drafts)
        if self._mesh is not None:
            from skypilot_tpu.parallel import sharding as sharding_lib  # pylint: disable=import-outside-toplevel
            drafts_dev = self._jax.device_put(
                drafts_dev,
                sharding_lib.spec_drafts_sharding(self._mesh))
        self._state, self._cache, finished, toks_d, counts_d = (
            self._dispatch_spec_step(drafts_dev))
        toks = np.asarray(toks_d)
        counts = np.asarray(counts_d)
        fins = np.asarray(finished)
        pushed = 0
        accepted = 0
        slot_ticks = 0
        for slot_id, request in list(live.items()):
            if request.done.is_set():
                continue
            slot_ticks += 1
            c = int(counts[slot_id])
            emitted = [int(t) for t in toks[slot_id, :c]]
            drafter = self._slots[slot_id].drafter
            if drafter is not None and emitted:
                drafter.observe(emitted)
            for token in emitted:
                request._push(token)  # pylint: disable=protected-access
            pushed += c
            accepted += max(c - 1, 0)
            span = request.span
            span.spec_steps += 1
            span.spec_proposed += k
            span.spec_accepted += max(c - 1, 0)
            _M_SPEC_ACCEPT_LEN.observe(float(max(c, 1)))
            if fins[slot_id]:
                live.pop(slot_id, None)
                self._slots[slot_id].request = None
                self._slots[slot_id].drafter = None
                self._release_slot_pages(slot_id)
                request._finish()  # pylint: disable=protected-access
        if pushed:
            self._record_tokens(pushed)
        with self._metrics_lock:
            self._ticks += 1
            self._spec_ticks += 1
            self._spec_slot_ticks += slot_ticks
            self._spec_proposed += k * n_live
            self._spec_accepted += accepted
        _M_TICKS.inc()
        _M_SPEC_PROPOSED.inc(k * n_live)
        _M_SPEC_ACCEPTED.inc(accepted)
        _M_BUSY_SLOTS.set(sum(1 for s in self._slots if s.active))

    # ------------------------------------------------- pipelined worker

    def _run(self) -> None:
        if not self.pipelined:
            self._run_legacy()
            return
        # Profiling lifecycle: one start/end pair brackets the worker's
        # whole run so journal replay can attribute the ring's ticks to
        # an engine incarnation (and see whether it died or drained).
        prof = self._profiler
        try:
            journal = profiling.serve_journal()
        except Exception:  # pylint: disable=broad-except
            journal = None
        if journal is not None:
            journal.append('tick_profile_start',
                           ring_ticks=prof.ring_ticks,
                           enabled=not prof.disabled)
        try:
            self._run_pipelined(prof)
        finally:
            if journal is not None:
                journal.append(
                    'tick_profile_end',
                    status='error' if self._failed is not None else 'ok',
                    ticks=prof.ticks)

    def _run_pipelined(self, prof: profiling.TickProfiler) -> None:
        import numpy as np  # pylint: disable=import-outside-toplevel
        # One in-flight tick: (state_handles, finished_handle,
        # [(slot_id, request), ...]) — read one tick behind.
        inflight: Optional[Tuple[Any, Any, List[Tuple[int, Any]]]] = None
        pending_prefills: Deque[scheduler.PendingPrefill] = (
            collections.deque())
        live: Dict[int, scheduler.Request] = {}  # slot -> decoding req
        while not self._stop.is_set():
            try:
                prof.begin_tick()
                self._queue.expire_stale()
                # Host ops (KV handoff imports) run between ticks: they
                # mutate self._cache, which only this thread owns.
                ran_ops = self._drain_host_ops()
                prof.lap('handoff', record=bool(ran_ops))
                # Cancelled or deadline-expired live requests: freeze
                # their slots on device before the next dispatch, free
                # them (and their KV pages) for admission.  Deadline
                # reaps finish with DeadlineExceeded so the HTTP front
                # answers 504 instead of a silent truncation.
                now = time.monotonic()
                reaped = [(i, r.cancelled) for i, r in live.items()
                          if r.cancelled or r.deadline_exceeded(now)]
                if reaped:
                    self._deactivate([i for i, _ in reaped])
                    for i, was_cancel in reaped:
                        request = live.pop(i)
                        self._slots[i].request = None
                        self._slots[i].drafter = None
                        self._release_slot_pages(i)
                        if was_cancel:
                            request._finish()  # pylint: disable=protected-access
                        else:
                            _M_DEADLINE_REAPED.inc()
                            request._finish(  # pylint: disable=protected-access
                                scheduler.DeadlineExceeded(
                                    'request deadline passed '
                                    'mid-generation'))
                # Admissions: hand free slots to queued requests.  The
                # prompt's chunks run interleaved with ticks below.
                # Page-pool exhaustion DEFERS (the request goes back to
                # the queue head and waits for pages to free or its
                # TTL) — it must never fail the engine.
                deferred = False
                admitted = False
                free = [i for i, s in enumerate(self._slots)
                        if not s.active]
                occupied = len(self._slots) - len(free)
                for slot_id in free:
                    # Fractional-role decode budget: stop admitting
                    # once occupied slots reach the decode-token cap
                    # (queued requests keep their WRR order; running
                    # decodes always finish).
                    if not self._queue.admission_allowed(occupied):
                        break
                    request = self._queue.pop()
                    if request is None:
                        break
                    admitted = True
                    try:
                        # Bind request identity so engine-worker log
                        # lines land in the structured ring under the
                        # request that triggered them (the worker
                        # thread never sees the HTTP front's context).
                        with logs_lib.bind(
                                request_id=request.request_id,
                                **(getattr(self, 'log_identity', None)
                                   or {})):
                            pending = self._start_admission(
                                slot_id, request)
                    except cache_manager.PagesExhausted:
                        self._queue.requeue_front(request)
                        with self._metrics_lock:
                            self._page_deferrals += 1
                        deferred = True
                        break
                    if pending is not None:
                        pending_prefills.append(pending)
                        occupied += 1
                    elif self._slots[slot_id].request is not None:
                        live[slot_id] = request
                        occupied += 1
                # The admit phase is everything since the handoff lap:
                # stale-expiry, reaps, and the admission loop (minus
                # any page-scatter laps a full-prefix admission took
                # inside _finish_prefill — laps are exclusive).
                prof.lap('admit',
                         record=bool(admitted or deferred or reaped))
                # At most ONE prefill chunk between ticks — the bound
                # on the ITL stall an admission can impose.
                if pending_prefills:
                    pending = pending_prefills.popleft()
                    done = self._advance_prefill(pending)
                    if done:
                        if self._slots[pending.slot_id].request is not None:
                            live[pending.slot_id] = pending.request
                    else:
                        pending_prefills.append(pending)
                # Dispatch tick t+1 BEFORE reading tick t: the host's
                # token fetch and stream bookkeeping below overlap the
                # device's compute of this new step.
                dispatched = None
                if live and self.spec_tokens:
                    # Speculative mode: synchronous multi-token verify
                    # ticks (see _spec_tick); `inflight` stays empty.
                    self._spec_tick(live)
                    prof.lap('spec-verify')
                elif live:
                    self._state, self._cache, finished = (
                        self._dispatch_step())
                    dispatched = (self._state, finished,
                                  list(live.items()))
                    prof.lap('decode-step')
                if inflight is not None:
                    state_t, finished_t, snapshot = inflight
                    toks = np.asarray(state_t['tokens'])
                    fins = np.asarray(finished_t)
                    pushed = 0
                    for slot_id, request in snapshot:
                        if request.done.is_set():
                            # Finished in an earlier tick (device froze
                            # the slot); this tick's value is a repeat.
                            continue
                        request._push(int(toks[slot_id]))  # pylint: disable=protected-access
                        pushed += 1
                        if fins[slot_id]:
                            live.pop(slot_id, None)
                            self._slots[slot_id].request = None
                            self._release_slot_pages(slot_id)
                            request._finish()  # pylint: disable=protected-access
                    if pushed:
                        self._record_tokens(pushed)
                    with self._metrics_lock:
                        self._ticks += 1
                    _M_TICKS.inc()
                    _M_BUSY_SLOTS.set(
                        sum(1 for s in self._slots if s.active))
                    prof.lap('sample')
                inflight = dispatched
                prof.end_tick()
                if (inflight is None and not live and
                        not pending_prefills):
                    if deferred:
                        # Pool exhausted and nothing running to free
                        # pages soon: throttle the retry loop (TTL
                        # expiry / cancel / submit backpressure are
                        # what resolve this state).
                        time.sleep(0.005)
                    else:
                        with self._cond:
                            with self._host_ops_lock:
                                ops_waiting = bool(self._host_ops)
                            if (not len(self._queue) and
                                    not ops_waiting and
                                    not self._stop.is_set()):
                                self._cond.wait(timeout=0.05)
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('batching engine tick failed')
                # The jit'd step donates the slot cache — after a
                # failure mid-step the cache buffers may be invalid, so
                # the engine CANNOT safely continue: fail everything in
                # flight, mark failed (submit() rejects from now on),
                # and exit the worker.
                self._fail_everything(e)
                return

    # --------------------------------------------------- legacy worker

    def _admit_legacy(self, slot_id: int,
                      request: scheduler.Request) -> None:
        """Pre-pipeline admission: the WHOLE prompt prefills inline
        (one long stall for every running request — what chunked
        prefill bounds).  Dense cache only."""
        if request.cancelled:
            request._finish()  # pylint: disable=protected-access
            return
        jnp = self._jnp
        slot = self._slots[slot_id]
        prompt = request.prompt_ids
        n = len(prompt)
        if self.cfg.n_experts > 0 and n > 0:
            logits, pre = self._prefill(
                self.params, jnp.asarray([prompt], jnp.int32))
            self._cache = self._insert(self._cache, slot_id, pre, n)
            first = int(jnp.argmax(logits[0]))
            request._push(first)  # pylint: disable=protected-access
            self._record_tokens(1)
            if (request.max_new_tokens <= 1 or
                    first in request.stop_ids):
                request._finish()  # pylint: disable=protected-access
                return
            slot.request = request
            slot.next_token = first
            return
        if n > 1:
            bucket = min(self._bucket(n - 1), self.max_len)
            padded = jnp.zeros((1, bucket), jnp.int32)
            padded = padded.at[0, :n - 1].set(
                jnp.asarray(prompt[:-1], jnp.int32))
            _, pre = self._prefill(self.params, padded)
            self._cache = self._insert(self._cache, slot_id, pre, n - 1)
        else:
            self._cache = dict(
                self._cache,
                lengths=self._cache['lengths'].at[slot_id].set(0))
        slot.request = request
        slot.next_token = int(prompt[-1])

    def _tick_legacy(self) -> None:
        """Pre-pipeline tick: eager per-slot token staging, one host
        sync per generated token, greedy only.  Kept as the A/B
        baseline `bench_serve.py` measures the pipelined loop against
        (and as a debugging fallback)."""
        jnp = self._jnp
        active = [i for i, s in enumerate(self._slots) if s.active]
        for i in active:
            req = self._slots[i].request
            if req.cancelled:
                self._slots[i].request = None
                req._finish()  # pylint: disable=protected-access
            elif req.deadline_exceeded():
                self._slots[i].request = None
                _M_DEADLINE_REAPED.inc()
                req._finish(scheduler.DeadlineExceeded(  # pylint: disable=protected-access
                    'request deadline passed mid-generation'))
        active = [i for i, s in enumerate(self._slots) if s.active]
        if not active:
            return
        tokens = self._tokens
        for i in active:
            tokens = tokens.at[i, 0].set(self._slots[i].next_token)
        logits, self._cache = self._legacy_step(self.params, tokens,
                                                self._cache)
        import numpy as np  # pylint: disable=import-outside-toplevel
        nxt = np.asarray(jnp.argmax(logits, axis=-1))  # one host sync
        pushed = 0
        for i in active:
            slot = self._slots[i]
            request = slot.request
            token = int(nxt[i])
            request._push(token)  # pylint: disable=protected-access
            pushed += 1
            finished = (len(request.tokens) >= request.max_new_tokens or
                        token in request.stop_ids)
            if finished:
                slot.request = None
                request._finish()  # pylint: disable=protected-access
            else:
                slot.next_token = token
        self._tokens = tokens
        self._record_tokens(pushed)
        with self._metrics_lock:
            self._ticks += 1
        _M_TICKS.inc()
        _M_BUSY_SLOTS.set(sum(1 for s in self._slots if s.active))

    def _run_legacy(self) -> None:
        while not self._stop.is_set():
            try:
                self._queue.expire_stale()
                idle = not any(s.active for s in self._slots)
                free = [i for i, s in enumerate(self._slots)
                        if not s.active]
                for slot_id in free:
                    request = self._pop_admitted()
                    if request is None:
                        if idle:
                            with self._cond:
                                if (not len(self._queue) and
                                        not self._stop.is_set()):
                                    self._cond.wait(timeout=0.05)
                            request = self._pop_admitted()
                        if request is None:
                            break
                    try:
                        self._admit_legacy(slot_id, request)
                        idle = False
                    except Exception as e:  # pylint: disable=broad-except
                        request._finish(e)  # pylint: disable=protected-access
                self._tick_legacy()
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('batching engine tick failed')
                self._fail_everything(e)
                return

    def _pop_admitted(self) -> Optional[scheduler.Request]:
        request = self._queue.pop()
        if request is not None:
            self._queue.record_admission(request)
        return request

    # ------------------------------------------------------------ failure

    def _fail_everything(self, e: Exception) -> None:
        self._failed = e
        self._stop.set()
        for slot in self._slots:
            if slot.request is not None:
                slot.request._finish(RuntimeError(  # pylint: disable=protected-access
                    f'batching engine failed: {e}'))
                slot.request = None
            slot.drafter = None
        self._queue.drain(
            lambda: RuntimeError(f'batching engine failed: {e}'))
        if self._kv is not None:
            self._kv.release_all()
        self._drain_host_ops()  # stop is set: pending imports error out
