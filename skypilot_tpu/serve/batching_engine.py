"""Continuous batching engine for the model server.

vLLM-style scheduling, rebuilt TPU-first (no reference equivalent —
SkyPilot ships no serving internals): a FIXED pool of KV-cache slots is
the batch dimension, so every jit'd shape is static.  Requests join a
running batch the moment a slot frees (no wait for the batch to drain),
and one `models.decode.batched_step` call advances every active slot a
token per engine tick — new arrivals ride along with half-finished
generations.

Exact-prefill trick for static shapes (dense models): the prompt's
first n-1 tokens are prefilled PADDED to a power-of-two bucket
(bounding compile count), the slot is inserted at length n-1, and the
LAST real prompt token is fed through the next batched step — it
overwrites the first pad position and attends only real keys, so
logits match unpadded decode exactly (tests pin this against
decode.generate).  MoE models instead prefill the FULL prompt unpadded
(the capacity dispatch couples every token, so both padding and the
n-1 split would perturb expert drops) and take their first token from
the prefill logits.

Greedy decoding (temperature 0) — the deterministic serving default;
per-request stop token and max_new_tokens.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


class _Request:

    def __init__(self, prompt_ids: List[int], max_new_tokens: int,
                 stop_token) -> None:
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = max_new_tokens
        # stop_token: None, a single id, or any iterable of ids (the
        # tokenizer's multi-EOS stop set — instruct checkpoints stop at
        # chat turn-end markers, not just the model-level EOS).
        if stop_token is None:
            self.stop_ids = frozenset()
        elif isinstance(stop_token, int):
            self.stop_ids = frozenset({stop_token})
        else:
            self.stop_ids = frozenset(int(t) for t in stop_token)
        self.done = threading.Event()
        self.tokens: List[int] = []
        self.error: Optional[Exception] = None
        self.cancelled = False
        # Streaming consumers read tokens as they are produced; the
        # None sentinel marks the end of the stream.
        self._live: 'queue.Queue[Optional[int]]' = queue.Queue()
        # _finish can race (worker finishing vs stop() failing-fast vs
        # submit() losing the stop race): first caller wins, later
        # calls are no-ops — otherwise two None sentinels truncate a
        # stream() and a success can be overwritten with an error.
        self._state_lock = threading.Lock()
        # Event-loop bridges (serve/async_server.py): called with each
        # token and a final None, from the engine worker thread, under
        # the state lock — watchers must be cheap and non-blocking
        # (call_soon_threadsafe qualifies).
        self._watchers: List[Any] = []

    def add_watcher(self, fn) -> None:
        """Subscribe fn(token|None) to this request's token stream;
        tokens already produced are replayed first, so late subscribers
        never miss a prefix (the admission path can push the first
        token before the caller gets the request handle back)."""
        with self._state_lock:
            for token in self.tokens:
                fn(token)
            if self.done.is_set():
                fn(None)
            else:
                self._watchers.append(fn)

    def _push(self, token: int) -> None:
        with self._state_lock:
            if self.done.is_set():
                # stop() already finished this request; a worker still
                # mid-tick must not append past the sentinel.
                return
            self.tokens.append(token)
            self._live.put(token)
            self._notify(token)

    def _finish(self, error: Optional[Exception] = None) -> None:
        with self._state_lock:
            if self.done.is_set():
                return
            self.error = error
            self.done.set()
            self._live.put(None)
            self._notify(None)
            self._watchers.clear()

    def _notify(self, token: Optional[int]) -> None:
        # A raising watcher (e.g. call_soon_threadsafe on a closed
        # event loop at shutdown) must not propagate into the engine
        # worker — that would fail the WHOLE engine for one dead
        # subscriber.  Drop it instead.
        for fn in list(self._watchers):
            try:
                fn(token)
            except Exception:  # pylint: disable=broad-except
                try:
                    self._watchers.remove(fn)
                except ValueError:
                    pass

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError('generation timed out')
        if self.error is not None:
            raise self.error
        return self.tokens

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as the engine produces them."""
        while True:
            token = self._live.get(timeout=timeout)
            if token is None:
                if self.error is not None:
                    raise self.error
                return
            yield token

    def cancel(self) -> None:
        """Stop generating for this request (client went away); the
        engine frees the slot on its next tick."""
        self.cancelled = True


class _Slot:

    def __init__(self) -> None:
        self.request: Optional[_Request] = None
        self.next_token = 0

    @property
    def active(self) -> bool:
        return self.request is not None


class ContinuousBatchingEngine:
    """Submit() from any thread; one worker thread owns the device."""

    def __init__(self, cfg, params, *, max_len: int = 512,
                 slots: int = 4) -> None:
        import jax
        import jax.numpy as jnp

        from skypilot_tpu.models import decode

        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self._jnp = jnp
        self._slots = [_Slot() for _ in range(slots)]
        self._cache = decode.init_slot_cache(cfg, slots, max_len)
        self._tokens = jnp.zeros((slots, 1), jnp.int32)
        self._queue: 'queue.Queue[_Request]' = queue.Queue()
        self._stop = threading.Event()

        def step(params, tokens, cache):
            return decode.batched_step(cfg, params, tokens, cache)

        self._step = jax.jit(step, donate_argnums=(2,))
        # Jitted prefill: one compile per prompt-length bucket (the
        # whole point of the bucket padding), not eager per-op dispatch
        # per admission.
        self._prefill = jax.jit(
            lambda params, toks: decode.prefill(cfg, params, toks,
                                                max_len=max_len))
        # Jitted in-place slot adoption: eager dynamic_update_slice
        # would materialize two full copies of the pool cache per
        # admission; donation lets XLA update it in place.
        self._insert = jax.jit(decode.insert_prefill,
                               donate_argnums=(0,))
        self._failed: Optional[Exception] = None
        self._tokens_generated = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ public

    def submit(self, prompt_ids: List[int], max_new_tokens: int,
               stop_token=None) -> _Request:
        """stop_token: None, one id, or an iterable of ids — the
        request finishes at the FIRST generated member of the set
        (multi-EOS: model-level EOS + chat turn-end markers)."""
        if not prompt_ids:
            raise ValueError('empty prompt')
        if max_new_tokens < 1:
            raise ValueError(
                f'max_new_tokens must be >= 1, got {max_new_tokens}')
        if len(prompt_ids) + max_new_tokens > self.max_len:
            raise ValueError(
                f'prompt {len(prompt_ids)} + new {max_new_tokens} '
                f'exceeds max_len {self.max_len}')
        if self._stop.is_set() or self._failed is not None:
            raise RuntimeError('batching engine is stopped'
                               if self._failed is None else
                               f'batching engine failed: {self._failed}')
        request = _Request(prompt_ids, max_new_tokens, stop_token)
        self._queue.put(request)
        if self._stop.is_set():
            # Lost the race with stop(): its drain may have already run,
            # so fail this request directly (idempotent via the event).
            if not request.done.is_set():
                request._finish(  # pylint: disable=protected-access
                    RuntimeError('batching engine stopped'))
        return request

    def generate(self, prompt_ids: List[int], max_new_tokens: int,
                 stop_token=None,
                 timeout: float = 600.0) -> List[int]:
        return self.submit(prompt_ids, max_new_tokens,
                           stop_token).result(timeout)

    def stats(self) -> Dict[str, Any]:
        """Live scheduling stats (surfaced via the server's /health —
        queue depth + slot occupancy are the autoscaling signals)."""
        busy = sum(1 for s in self._slots if s.active)
        return {
            'slots': len(self._slots),
            'busy_slots': busy,
            'queued_requests': self._queue.qsize(),
            'tokens_generated': self._tokens_generated,
            'failed': self._failed is not None,
        }

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        # Fail fast for anything still queued or in flight — callers
        # must not sit out their full result() timeout at shutdown.
        shutdown_error = RuntimeError('batching engine stopped')
        while True:
            try:
                request = self._queue.get_nowait()
            except queue.Empty:
                break
            request._finish(shutdown_error)  # pylint: disable=protected-access
        for slot in self._slots:
            if slot.request is not None:
                slot.request._finish(shutdown_error)  # pylint: disable=protected-access
                slot.request = None

    # ------------------------------------------------------------ worker

    def _bucket(self, n: int) -> int:
        for b in _PREFILL_BUCKETS:
            if n <= b:
                return b
        return n

    def _admit(self, slot_id: int, request: _Request) -> None:
        if request.cancelled:
            # Cancelled while queued: don't pay a prefill (possibly a
            # fresh bucket compile) for a dead request.
            request._finish()  # pylint: disable=protected-access
            return
        jnp = self._jnp
        slot = self._slots[slot_id]
        prompt = request.prompt_ids
        n = len(prompt)
        if self.cfg.n_experts > 0 and n > 0:
            # MoE: the capacity dispatch couples EVERY prompt token, so
            # both pad tokens and an n-1/last-token split change which
            # tokens drop — only a full-prompt unpadded prefill matches
            # the single-sequence reference.  The first generated token
            # therefore comes from the prefill logits (one compile per
            # distinct MoE prompt length).
            logits, pre = self._prefill(
                self.params, jnp.asarray([prompt], jnp.int32))
            self._cache = self._insert(self._cache, slot_id, pre, n)
            first = int(jnp.argmax(logits[0]))
            request._push(first)  # pylint: disable=protected-access
            self._tokens_generated += 1
            if (request.max_new_tokens <= 1 or
                    first in request.stop_ids):
                request._finish()  # pylint: disable=protected-access
                return
            slot.request = request
            slot.next_token = first
            return
        if n > 1:
            # Dense: prefill tokens [0, n-1) padded to a bucket (capped
            # at max_len — the cache cannot hold more); pad keys land
            # at positions >= n-1 where they are masked (and the first
            # one is overwritten by the real last token's step).
            bucket = min(self._bucket(n - 1), self.max_len)
            padded = jnp.zeros((1, bucket), jnp.int32)
            padded = padded.at[0, :n - 1].set(
                jnp.asarray(prompt[:-1], jnp.int32))
            _, pre = self._prefill(self.params, padded)
            self._cache = self._insert(self._cache, slot_id, pre, n - 1)
        else:
            # Single-token prompt: empty slot; stale keys are masked
            # (lengths = 0) and position 0 is overwritten next step.
            self._cache = dict(
                self._cache,
                lengths=self._cache['lengths'].at[slot_id].set(0))
        slot.request = request
        slot.next_token = int(prompt[-1])

    def _tick(self) -> None:
        jnp = self._jnp
        active = [i for i, s in enumerate(self._slots) if s.active]
        if not active:
            return
        # Free slots whose client cancelled before spending a tick on
        # them (the cancel flag is read once per tick).
        for i in active:
            req = self._slots[i].request
            if req.cancelled:
                self._slots[i].request = None
                req._finish()  # pylint: disable=protected-access
        active = [i for i, s in enumerate(self._slots) if s.active]
        if not active:
            return
        tokens = self._tokens
        for i in active:
            tokens = tokens.at[i, 0].set(self._slots[i].next_token)
        logits, self._cache = self._step(self.params, tokens, self._cache)
        import numpy as np  # pylint: disable=import-outside-toplevel
        nxt = np.asarray(jnp.argmax(logits, axis=-1))  # one host sync
        for i in active:
            slot = self._slots[i]
            request = slot.request
            token = int(nxt[i])
            request._push(token)  # pylint: disable=protected-access
            self._tokens_generated += 1
            finished = (len(request.tokens) >= request.max_new_tokens or
                        token in request.stop_ids)
            if finished:
                slot.request = None
                request._finish()  # pylint: disable=protected-access
            else:
                slot.next_token = token
        self._tokens = tokens

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                # Fill free slots from the queue; block briefly when
                # fully idle so shutdown stays responsive.
                idle = not any(s.active for s in self._slots)
                free = [i for i, s in enumerate(self._slots)
                        if not s.active]
                admitted = False
                for slot_id in free:
                    try:
                        request = self._queue.get(
                            timeout=0.05 if idle and not admitted
                            else 0.0)
                    except queue.Empty:
                        break
                    try:
                        self._admit(slot_id, request)
                        admitted = True
                    except Exception as e:  # pylint: disable=broad-except
                        request._finish(e)  # pylint: disable=protected-access
                self._tick()
            except Exception as e:  # pylint: disable=broad-except
                logger.exception('batching engine tick failed')
                # The jit'd step donates the slot cache — after a
                # failure mid-step the cache buffers may be invalid, so
                # the engine CANNOT safely continue: fail everything in
                # flight, mark failed (submit() rejects from now on),
                # and exit the worker.
                self._failed = e
                self._stop.set()
                for slot in self._slots:
                    if slot.request is not None:
                        slot.request._finish(RuntimeError(  # pylint: disable=protected-access
                            f'batching engine failed: {e}'))
                        slot.request = None
                while True:
                    try:
                        request = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    request._finish(RuntimeError(  # pylint: disable=protected-access
                        f'batching engine failed: {e}'))
                return
