"""Autoscalers: decide the target replica count from request rate.

Parity: /root/reference/sky/serve/autoscalers.py:145-530
(RequestRateAutoscaler with upscale/downscale hysteresis,
FallbackRequestRateAutoscaler mixing spot + on-demand).  Pure logic —
no clock or cluster access — so it is directly unit-testable; the
controller owns time and actuation.
"""
from __future__ import annotations

import dataclasses
import math
import typing
from typing import List, Optional

if typing.TYPE_CHECKING:
    from skypilot_tpu.serve.service_spec import SkyServiceSpec

# Window over which QPS is measured (parity: reference
# autoscalers.py qps_window_size).
QPS_WINDOW_SIZE_SECONDS = 60.0


@dataclasses.dataclass
class AutoscalerDecision:
    target_num_replicas: int
    # For the fallback autoscaler: how many of the target should be
    # on-demand (the rest spot).
    num_ondemand: int = 0


class RequestRateAutoscaler:
    """Scale to ceil(qps / target_qps_per_replica) with hysteresis.

    `spec` is anything carrying the pool-shaped attributes (a
    SkyServiceSpec, or one service_spec.RolePool when each
    disaggregated role pool scales independently)."""

    def __init__(self, spec: 'SkyServiceSpec') -> None:
        self.min_replicas = spec.min_replicas
        self.max_replicas = spec.max_replicas
        self.target_qps_per_replica = spec.target_qps_per_replica
        self.target_slot_utilization = getattr(
            spec, 'target_slot_utilization', None)
        self.upscale_delay_seconds = spec.upscale_delay_seconds
        self.downscale_delay_seconds = spec.downscale_delay_seconds
        self.target_num_replicas = spec.min_replicas
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None
        self.request_timestamps: List[float] = []
        # Latest per-replica decode load (busy_slots/slots fractions
        # from the replicas' /health engine stats); empty until the
        # controller's probe loop reports.
        self.replica_loads: List[float] = []
        # Smoothed QPS from the controller's fleet aggregator
        # (windowed rate of the LB route counter).  When present it
        # replaces the raw timestamp count in the scaling rule: a
        # one-scrape burst no longer whipsaws the target.  None until
        # the aggregator has enough history — the instantaneous
        # signal then applies unchanged.
        self.windowed_qps: Optional[float] = None

    # ------------------------------------------------------------- inputs

    def warm_start(self, live_replicas: int) -> None:
        """Controller crash recovery: seed the scale target from the
        fleet actually running instead of min_replicas.  A restarted
        controller has no request history yet — without this, its
        first reconcile pass reads 'target = min' and retires healthy
        replicas (a scale-to-min cliff under live load).  The QPS
        history refills from the LB's next sync."""
        if live_replicas > 0:
            self.target_num_replicas = max(
                self.min_replicas,
                min(live_replicas, self.max_replicas))

    def carry_over(self, old: 'RequestRateAutoscaler') -> None:
        """Adopt a predecessor's live state across a service update.

        A version reload replaces the autoscaler object; without this,
        target_num_replicas collapses to min_replicas and the request
        history vanishes — mid-update that reads as "new fleet of 1 is
        enough" and blue_green flips a 5-replica service onto a single
        replica (a capacity cliff under live load)."""
        self.request_timestamps = list(old.request_timestamps)
        self.windowed_qps = old.windowed_qps
        self.target_num_replicas = max(
            self.min_replicas,
            min(old.target_num_replicas, self.max_replicas))

    def collect_request_information(self, timestamps: List[float],
                                    now: float) -> None:
        self.request_timestamps.extend(timestamps)
        cutoff = now - QPS_WINDOW_SIZE_SECONDS
        self.request_timestamps = [t for t in self.request_timestamps
                                   if t >= cutoff]

    def collect_replica_load(self, loads: List[float]) -> None:
        """Report per-replica decode saturation (busy_slots/slots from
        each ready replica's /health engine stats).  Lets the
        autoscaler scale on DECODE saturation, not just QPS: long
        generations pin every KV slot at a QPS the request-rate signal
        reads as idle."""
        self.replica_loads = [max(0.0, min(1.0, float(u)))
                              for u in loads]

    def collect_windowed_signals(self, qps: Optional[float] = None,
                                 loads: Optional[List[float]] = None
                                 ) -> None:
        """Adopt the fleet aggregator's smoothed signals (PR 11):
        windowed per-role QPS and windowed per-replica loads.  None
        for either leaves the corresponding instantaneous signal in
        force — a cold or scrape-less controller behaves exactly as
        before."""
        self.windowed_qps = (None if qps is None
                             else max(0.0, float(qps)))
        if loads is not None:
            self.collect_replica_load(loads)

    def _desired_from_load(self) -> int:
        """ceil(ready * mean_util / target_util), the slot-utilization
        analogue of the QPS rule; 0 when the signal is absent."""
        if self.target_slot_utilization is None or not self.replica_loads:
            return 0
        mean_util = (sum(self.replica_loads) /
                     len(self.replica_loads))
        return math.ceil(len(self.replica_loads) * mean_util /
                         self.target_slot_utilization)

    def _desired_from_qps(self, now: float) -> int:
        del now
        if (self.target_qps_per_replica is None and
                self.target_slot_utilization is None):
            return self.target_num_replicas
        desired = self._desired_from_load()
        if self.target_qps_per_replica is not None:
            # The aggregator's windowed rate when available (smoothed
            # over the scrape history), else the raw timestamp count.
            qps = (self.windowed_qps
                   if self.windowed_qps is not None else
                   len(self.request_timestamps) /
                   QPS_WINDOW_SIZE_SECONDS)
            desired = max(desired,
                          math.ceil(qps / self.target_qps_per_replica))
        return max(self.min_replicas,
                   min(self.max_replicas, desired))

    # ----------------------------------------------------------- decision

    def evaluate_scaling(self, now: float) -> AutoscalerDecision:
        desired = self._desired_from_qps(now)
        if desired > self.target_num_replicas:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= self.upscale_delay_seconds:
                self.target_num_replicas = desired
                self._upscale_since = None
        elif desired < self.target_num_replicas:
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if now - self._downscale_since >= self.downscale_delay_seconds:
                self.target_num_replicas = desired
                self._downscale_since = None
        else:
            self._upscale_since = None
            self._downscale_since = None
        return AutoscalerDecision(self.target_num_replicas)


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Spot replicas with an on-demand safety base: keep
    `base_ondemand_fallback_replicas` on-demand replicas regardless of
    scale; the remainder of the target rides spot capacity.

    Parity: reference autoscalers.py:480-530.
    """

    def __init__(self, spec: 'SkyServiceSpec') -> None:
        super().__init__(spec)
        self.base_ondemand = spec.base_ondemand_fallback_replicas

    def evaluate_scaling(self, now: float) -> AutoscalerDecision:
        decision = super().evaluate_scaling(now)
        decision.num_ondemand = min(self.base_ondemand,
                                    decision.target_num_replicas)
        return decision


def make_autoscaler(spec: 'SkyServiceSpec',
                    role: Optional[str] = None) -> RequestRateAutoscaler:
    """Build the autoscaler for a service — or for ONE of its role
    pools (`role=...`), each of which holds its own targets/bounds so
    a prefill burst scales the prefill pool without churning decode
    replicas."""
    pool = spec if role is None else spec.role_specs[role]
    if getattr(pool, 'base_ondemand_fallback_replicas', 0) > 0:
        return FallbackRequestRateAutoscaler(pool)
    return RequestRateAutoscaler(pool)
