"""Router brain store: the shared state behind the router tier.

PR 8 put the routing brain (ready/retired sets, prefix-affinity map,
in-flight counts) directly inside `serve/router.py` as process-local
dicts — correct for one router, and a hard wall for N of them: two
routers with private brains double-prefill repeat prefixes, resurrect
replicas their sibling retired, and balance against stale in-flight
views.  This module extracts that state behind a store interface:

- **InProcessBrainStore** — the PR 8 dicts behind the interface, one
  lock.  A single router (the default) is bit-for-bit the old
  behavior; N routers *in one process* (the router tier's local mode)
  simply share one instance and the lock makes every route decision
  atomic across the tier.
- **ReplicatedBrainStore** — wraps an in-process store and fans
  retire / affinity deltas to sibling router instances over the
  ``POST /lb/state`` control-plane route, so routers in *separate
  processes* converge without waiting out a controller sync.  Applies
  of replicated deltas never re-fan (no echo storms).

Retired entries carry an **epoch** (generation counter).  A retirement
at epoch `e` can only be cleared by a controller view stamped with
`retired_epoch >= e` — a stale sync captured before the retirement can
never resurrect the replica on any router (the PR 15 two-router
regression).  Epochs are seeded from the wall clock so a restarted
controller keeps issuing larger ones.

The store holds *state*; `serve/router.py` keeps the selection logic
(role dispatch, affinity, least-loaded ranking) and takes the store's
lock around each decision.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import http_protocol

logger = sky_logging.init_logger(__name__)


def next_epoch_seed() -> int:
    """Starting value for a fresh epoch counter: wall-clock seconds,
    so counters restarted in a new process still dominate epochs
    issued before the restart."""
    return int(time.time())


def encode_affinity_key(key: Hashable) -> Any:
    """JSON-safe form of a prompt prefix key (router.prompt_key returns
    ('ids', tuple) / ('text', str) — tuples don't survive JSON)."""
    if isinstance(key, tuple):
        return [encode_affinity_key(k) for k in key]
    return key


def decode_affinity_key(wire: Any) -> Hashable:
    if isinstance(wire, list):
        return tuple(decode_affinity_key(k) for k in wire)
    return wire


class InProcessBrainStore:
    """The routing brain's state, one lock.  Thread-safe; shared by
    every router instance of an in-process tier."""

    def __init__(self, affinity_capacity: int = 4096) -> None:
        self.lock = threading.RLock()
        # url -> ReplicaEndpoint (typed by serve/router.py; the store
        # treats endpoints as opaque values keyed by url).
        self.endpoints: Dict[str, Any] = {}
        # prefix key -> url last served, LRU-bounded.
        self.affinity: 'collections.OrderedDict[Hashable, str]' = (
            collections.OrderedDict())
        self.affinity_capacity = int(affinity_capacity)
        self.inflight: Dict[str, int] = {}
        self.affinity_hits = 0
        self.affinity_misses = 0
        # url -> retirement epoch.  Filtered out of every ready view
        # until a controller sync stamped with a >= epoch clears it.
        self._retired: Dict[str, int] = {}
        self._epochs = itertools.count(next_epoch_seed())

    # ------------------------------------------------------------ fleet

    def set_endpoints(self, endpoints: Dict[str, Any]) -> None:
        with self.lock:
            self.endpoints = dict(endpoints)
            self.drop_stale_affinity_locked()

    def drop_stale_affinity_locked(self) -> None:
        for key in [k for k, url in self.affinity.items()
                    if url not in self.endpoints]:
            del self.affinity[key]

    # ---------------------------------------------------------- retired

    def next_local_epoch(self) -> int:
        """Epoch for a locally-originated retirement (an `/lb/retire`
        nudge that carried none)."""
        return next(self._epochs)

    def retire(self, url: str, epoch: Optional[int] = None) -> int:
        """Mark a url retired at `epoch` (a later epoch wins; an older
        one never downgrades).  Returns the effective epoch."""
        with self.lock:
            if epoch is None:
                epoch = self.next_local_epoch()
            epoch = max(int(epoch), self._retired.get(url, 0))
            self._retired[url] = epoch
            return epoch

    def retired_urls(self) -> Dict[str, int]:
        with self.lock:
            return dict(self._retired)

    def is_retired(self, url: str) -> bool:
        with self.lock:
            return url in self._retired

    def reconcile_retired(self, urls: List[str],
                          view_epoch: Optional[int]) -> List[str]:
        """Apply a controller ready-set view and return it with retired
        urls filtered out.

        An entry retired at epoch `e` is cleared only by a view stamped
        `view_epoch >= e`: the controller demonstrably processed that
        retirement, so if the url is listed again it was *re-readied*,
        not resurrected by a stale snapshot.  Unstamped (legacy) views
        keep filtering listed urls and only garbage-collect entries
        whose url left the fleet entirely."""
        with self.lock:
            kept: Dict[str, int] = {}
            for url, e in self._retired.items():
                if view_epoch is not None and int(view_epoch) >= e:
                    continue                    # confirmed by controller
                if view_epoch is None and url not in urls:
                    continue                    # legacy GC: url is gone
                kept[url] = e
            self._retired = kept
            return [u for u in urls if u not in kept]

    # --------------------------------------------------------- affinity

    def record_affinity(self, key: Hashable, url: str) -> None:
        with self.lock:
            self.affinity[key] = url
            self.affinity.move_to_end(key)
            while len(self.affinity) > self.affinity_capacity:
                self.affinity.popitem(last=False)

    def affinity_target(self, key: Hashable) -> Optional[str]:
        with self.lock:
            return self.affinity.get(key)

    # --------------------------------------------------------- inflight

    def acquire(self, url: str) -> None:
        with self.lock:
            self.inflight[url] = self.inflight.get(url, 0) + 1

    def release(self, url: str) -> None:
        with self.lock:
            n = self.inflight.get(url, 0) - 1
            if n <= 0:
                self.inflight.pop(url, None)
            else:
                self.inflight[url] = n

    def inflight_total(self) -> int:
        with self.lock:
            return sum(self.inflight.values())


class ReplicatedBrainStore(InProcessBrainStore):
    """An in-process store that replicates retire / affinity deltas to
    sibling router instances over ``POST /lb/state``.

    Replication is best-effort and asymmetric by design: retirements
    and affinity pins fan out immediately (they are the correctness-
    and latency-critical deltas), while the full ready set converges
    through the controller's own push/sync to every instance.  A
    delta applied *from* a sibling sets ``replicated=True`` so the
    apply never fans back out (no echo loops)."""

    def __init__(self, affinity_capacity: int = 4096,
                 post: Optional[Callable[..., Any]] = None) -> None:
        super().__init__(affinity_capacity=affinity_capacity)
        # Sibling /lb/ control-plane base urls, e.g.
        # ['http://127.0.0.1:5001', ...] (never includes self).
        self._peers: List[str] = []
        self._post = post or self._default_post
        self.push_failures = 0

    def set_peers(self, peer_urls: List[str]) -> None:
        with self.lock:
            self._peers = list(peer_urls)

    def peers(self) -> List[str]:
        with self.lock:
            return list(self._peers)

    @staticmethod
    def _default_post(url: str, payload: Dict[str, Any],
                      timeout: float = 2.0) -> None:
        import requests  # pylint: disable=import-outside-toplevel
        requests.post(url, json=payload, timeout=timeout)

    def _fan_out(self, payload: Dict[str, Any]) -> None:
        from skypilot_tpu.chaos import injector  # pylint: disable=import-outside-toplevel
        for peer in self.peers():
            try:
                if injector.inject('serve.router_push', peer=peer):
                    raise RuntimeError('state push denied (chaos)')
                self._post(peer + http_protocol.LB_STATE, payload)
            except Exception as e:  # pylint: disable=broad-except
                # Best effort: the controller's periodic state push is
                # the convergence backstop for a missed delta.
                self.push_failures += 1
                logger.debug(f'router state push to {peer} failed: {e}')

    def retire(self, url: str, epoch: Optional[int] = None,
               replicated: bool = False) -> int:
        epoch = super().retire(url, epoch)
        if not replicated:
            self._fan_out({'retire': {'url': url, 'epoch': epoch}})
        return epoch

    def record_affinity(self, key: Hashable, url: str,
                        replicated: bool = False) -> None:
        super().record_affinity(key, url)
        if not replicated:
            self._fan_out({'affinity': {
                'key': encode_affinity_key(key), 'url': url}})

    def apply_delta(self, payload: Dict[str, Any]) -> None:
        """Apply a sibling's replicated delta (never re-fans)."""
        retire = payload.get('retire')
        if isinstance(retire, dict) and retire.get('url'):
            self.retire(retire['url'], retire.get('epoch'),
                        replicated=True)
        affinity = payload.get('affinity')
        if isinstance(affinity, dict) and affinity.get('url'):
            key = decode_affinity_key(affinity.get('key'))
            if key is not None:
                self.record_affinity(key, affinity['url'],
                                     replicated=True)


def make_store(replicated: bool = False,
               affinity_capacity: int = 4096,
               post: Optional[Callable[..., Any]] = None
               ) -> InProcessBrainStore:
    if replicated:
        return ReplicatedBrainStore(affinity_capacity=affinity_capacity,
                                    post=post)
    return InProcessBrainStore(affinity_capacity=affinity_capacity)


def consistent_hash(value: str) -> int:
    """Stable 64-bit hash for the ring (md5 head; Python's `hash` is
    salted per process, useless for cross-router agreement)."""
    import hashlib  # pylint: disable=import-outside-toplevel
    digest = hashlib.md5(value.encode('utf-8', 'surrogatepass')).digest()
    return int.from_bytes(digest[:8], 'big')


class HashRing:
    """Consistent-hash ring mapping prefix keys to router instances.

    Virtual nodes smooth the split; the classic property holds: when
    an instance joins or leaves, only the keys in its arcs move
    (~K/N of them), every other key keeps its owner — which is what
    keeps repeat prefixes landing on the same router (and therefore
    the same affinity-pinned replica) across tier resizes."""

    def __init__(self, vnodes: int = 64) -> None:
        self._vnodes = int(vnodes)
        self._ring: List[Tuple[int, str]] = []   # (point, member) sorted
        self._members: List[str] = []

    def members(self) -> List[str]:
        return list(self._members)

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.append(member)
        for i in range(self._vnodes):
            self._ring.append(
                (consistent_hash(f'{member}#{i}'), member))
        self._ring.sort()

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.remove(member)
        self._ring = [(p, m) for p, m in self._ring if m != member]

    def owner(self, key: Hashable) -> Optional[str]:
        """The single instance that owns `key` (clockwise successor on
        the ring); None on an empty ring."""
        if not self._ring:
            return None
        point = consistent_hash(repr(key))
        lo, hi = 0, len(self._ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._ring[mid][0] < point:
                lo = mid + 1
            else:
                hi = mid
        return self._ring[lo % len(self._ring)][1]
