"""Router tier: N stateless router instances behind one front door.

One `serve/load_balancer.py` process was the last single point the
whole fleet funneled through — ROADMAP item 4's "millions of users do
not fit through one router".  This module runs N of them as a tier:

- **Shared brain.**  Every instance routes against ONE brain store
  (`serve/brain_store.py`): the ready set, prefix-affinity map,
  in-flight counts, and epoch-guarded retired set are tier-wide, so
  any instance retiring a replica retires it everywhere and two
  instances never double-commit the same affinity slot.  In-process
  tiers share the store object; cross-process instances replicate
  deltas over ``POST /lb/state``.
- **Consistent hashing.**  The prefix-affinity key maps onto a
  virtual-node hash ring over the instances: repeat prefixes enter
  through the same router (whose affinity map then pins the same
  replica), and an instance joining or leaving moves only ~K/N keys —
  every other session keeps its router AND its replica-side prefix
  cache.
- **Controller pushes.**  The controller reconciles the tier like a
  role pool (service spec ``routers: {replicas, qos}``), pushing
  ready/retired deltas to every instance over the generalized
  ``/lb/`` control plane the moment the fleet changes.
- **Death is boring.**  Instances are stateless; when one dies
  (`router_instance_death` chaos scenario) the ring re-homes its keys
  to survivors, the shared store keeps every retirement and pin, and
  in-flight requests retry through a sibling with zero lost requests.

Journal: `router_instance_start` / `router_instance_end` (process
scope) bracket each instance's life; the chaos invariants replay them
alongside the `lb_*` / `qos_*` events.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Hashable, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import brain_store as brain_store_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import qos as qos_lib
from skypilot_tpu.serve import router as router_lib

logger = sky_logging.init_logger(__name__)

DEFAULT_VNODES = 64


class RouterInstance:
    """One running router of the tier: an id, a load balancer bound to
    its own port, and liveness state."""

    def __init__(self, instance_id: str,
                 balancer: lb_lib.SkyServeLoadBalancer) -> None:
        self.instance_id = instance_id
        self.balancer = balancer
        self.alive = False

    @property
    def port(self) -> int:
        return self.balancer.port

    @property
    def url(self) -> str:
        return f'http://127.0.0.1:{self.balancer.port}'


class RouterTier:
    """N router instances sharing one brain store and one hash ring."""

    def __init__(self, controller_url: str, replicas: int = 1,
                 qos: Optional[Dict[str, Any]] = None,
                 region: Optional[str] = None,
                 affinity_capacity: int = 4096,
                 vnodes: int = DEFAULT_VNODES,
                 router_kwargs: Optional[Dict[str, Any]] = None) -> None:
        self.controller_url = controller_url
        self.qos = dict(qos or {})
        self.region = region
        self._router_kwargs = dict(router_kwargs or {})
        self._affinity_capacity = int(affinity_capacity)
        # One shared in-process store: every instance's Router takes
        # the same lock, so tier-wide decisions stay atomic.
        self.store = brain_store_lib.InProcessBrainStore(
            affinity_capacity=self._affinity_capacity)
        self.ring = brain_store_lib.HashRing(vnodes=vnodes)
        self._lock = threading.Lock()
        self._instances: Dict[str, RouterInstance] = {}
        self._next_index = 0
        self._want = max(1, int(replicas))

    # -------------------------------------------------------- lifecycle

    def _spawn_locked(self) -> RouterInstance:
        instance_id = f'router-{self._next_index}'
        self._next_index += 1
        balancer = lb_lib.SkyServeLoadBalancer(
            self.controller_url,
            router=router_lib.Router(
                store=self.store, region=self.region,
                **self._router_kwargs),
            router_id=instance_id, qos=self.qos)
        port = balancer.start()
        instance = RouterInstance(instance_id, balancer)
        instance.alive = True
        self._instances[instance_id] = instance
        self.ring.add(instance_id)
        # Same gating as the LB's routing events: the journal only
        # records while a scenario/operator is watching.
        lb_lib._journal_handoff(  # pylint: disable=protected-access
            'router_instance_start', instance=instance_id, port=port,
            tier_size=len(self._instances))
        logger.info(f'router tier: {instance_id} up on :{port} '
                    f'({len(self._instances)} instance(s))')
        return instance

    def start(self) -> List[int]:
        """Bring the tier to its target size; returns instance ports
        in instance order."""
        with self._lock:
            while len(self._instances) < self._want:
                self._spawn_locked()  # skytpu: lint-ok[blocking-under-lock] reason=tier membership changes are rare operator/controller actions; the lock makes ring+instance-map updates atomic against url_for
            return [i.port for i in self._instances.values()]

    def reconcile(self, replicas: int) -> List[int]:
        """Converge the tier to `replicas` instances (the controller
        calls this like a role-pool autoscaler target): spawn up,
        retire down (newest first, like retirement_order)."""
        self._want = max(1, int(replicas))
        with self._lock:
            while len(self._instances) < self._want:
                self._spawn_locked()  # skytpu: lint-ok[blocking-under-lock] reason=tier membership changes are rare operator/controller actions; the lock makes ring+instance-map updates atomic against url_for
            while len(self._instances) > self._want:
                victim = list(self._instances)[-1]
                self._stop_locked(victim, reason='scale_down')  # skytpu: lint-ok[blocking-under-lock] reason=tier membership changes are rare operator/controller actions; the lock makes ring+instance-map updates atomic against url_for
            return [i.port for i in self._instances.values()]

    def _stop_locked(self, instance_id: str, reason: str) -> None:
        instance = self._instances.pop(instance_id, None)
        if instance is None:
            return
        self.ring.remove(instance_id)
        instance.alive = False
        try:
            instance.balancer.stop()
        except Exception:  # pylint: disable=broad-except
            pass
        lb_lib._journal_handoff(  # pylint: disable=protected-access
            'router_instance_end', instance=instance_id, reason=reason,
            tier_size=len(self._instances))
        logger.info(f'router tier: {instance_id} down ({reason}; '
                    f'{len(self._instances)} left)')

    def stop_instance(self, instance_id: str,
                      reason: str = 'killed') -> None:
        """Take one instance down (chaos / operator action).  Its ring
        arcs re-home to survivors; the shared store keeps every
        retirement and affinity pin."""
        with self._lock:
            self._stop_locked(instance_id, reason=reason)  # skytpu: lint-ok[blocking-under-lock] reason=tier membership changes are rare operator/controller actions; the lock makes ring+instance-map updates atomic against url_for

    def stop(self) -> None:
        with self._lock:
            for instance_id in list(self._instances):
                self._stop_locked(instance_id, reason='shutdown')  # skytpu: lint-ok[blocking-under-lock] reason=tier membership changes are rare operator/controller actions; the lock makes ring+instance-map updates atomic against url_for

    # ------------------------------------------------------------ query

    def instances(self) -> List[RouterInstance]:
        with self._lock:
            return list(self._instances.values())

    def ports(self) -> List[int]:
        with self._lock:
            return [i.port for i in self._instances.values()]

    def owner(self, key: Hashable) -> Optional[RouterInstance]:
        """The single instance that owns a prefix key (front doors /
        tests dispatch repeat prefixes through it so the affinity map
        is written by one router and replicated to the rest)."""
        with self._lock:
            instance_id = self.ring.owner(key)
            return self._instances.get(instance_id) \
                if instance_id else None

    def url_for(self, prompt_ids: Optional[List[int]] = None,
                text: Optional[str] = None) -> Optional[str]:
        """Front-door resolution: the owning instance's url for a
        prompt (falls back to any live instance for key-less
        requests)."""
        key = router_lib.prompt_key(prompt_ids=prompt_ids, text=text)
        instance = self.owner(key) if key is not None else None
        if instance is None:
            live = self.instances()
            instance = live[0] if live else None
        return instance.url if instance else None

    def set_replicas(self, replicas: List[Dict[str, Any]]) -> None:
        """Install the ready set tier-wide (the brain store is shared,
        but each instance also tracks its own ready_urls list)."""
        for instance in self.instances():
            instance.balancer.set_replicas(replicas)

    def apply_state(self, payload: Dict[str, Any]) -> None:
        """Apply a controller state push to every instance (in-process
        fast path of the POST /lb/state plane)."""
        for instance in self.instances():
            instance.balancer.apply_state(payload)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'instances': len(self._instances),
                'want': self._want,
                'ports': [i.port for i in self._instances.values()],
                'ring_members': self.ring.members(),
                'qos': {name: spec.to_dict() for name, spec in
                        qos_lib.from_config(self.qos).items()},
            }
