"""Serve client API: up / update / down / status / tail_logs.

Parity: /root/reference/sky/serve/core.py:95-648.  The service daemon
(controller + LB) runs as a detached local process by default — the
same supervision code the reference runs on a controller VM.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import http_protocol
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ServiceStatus
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)


def _yaml_dir() -> str:
    return common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'serve'))


def _validate(task: task_lib.Task, service_name: str) -> None:
    if task.service is None:
        raise exceptions.InvalidTaskError(
            'Task must carry a `service:` section for serve.up().')
    common_utils.check_cluster_name_is_valid(service_name)


def up(task: task_lib.Task, service_name: Optional[str] = None,
       *, detach: bool = True) -> Tuple[str, str]:
    """Start a service; returns (service_name, endpoint_url).

    With `serve.controller.mode: cluster` the service daemon
    (controller + LB) runs on a provisioned controller cluster
    (reference serve/core.py:203 behavior) instead of a local process;
    replica clusters are then launched FROM that cluster and survive
    this client machine going away.
    """
    service_name = service_name or task.name or 'service'
    _validate(task, service_name)
    from skypilot_tpu.serve import utils as serve_utils  # pylint: disable=import-outside-toplevel
    if serve_utils.controller_mode() == 'cluster':
        return _up_on_cluster(task, service_name, detach=detach)
    if serve_state.get_service(service_name) is not None:
        raise exceptions.InvalidTaskError(
            f'Service {service_name!r} already exists; use '
            'serve.update() for in-place updates.')
    yaml_path = os.path.join(_yaml_dir(), f'{service_name}.yaml')
    common_utils.dump_yaml(yaml_path, task.to_yaml_config())
    serve_state.add_service(service_name,
                            task.service.to_yaml_config(), yaml_path)
    _start_daemon(service_name)
    endpoint = _wait_for_endpoint(service_name)
    if not detach:
        _wait_until_ready(service_name)
    return service_name, endpoint


def _up_on_cluster(task: task_lib.Task, service_name: str,
                   *, detach: bool) -> Tuple[str, str]:
    from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import resources as resources_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import constants as serve_constants  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.serve import utils as serve_utils  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import controller_utils  # pylint: disable=import-outside-toplevel

    if serve_utils.run_if_controller_exists(
            serve_utils.ServeCodeGen.get_service(service_name),
            'SERVE_RECORD:') is not None:
        raise exceptions.InvalidTaskError(
            f'Service {service_name!r} already exists; use '
            'serve.update() for in-place updates.')
    # The controller cluster cannot see this machine's filesystem: the
    # SERVICE task's local paths must be translated before handoff
    # (replicas launch from the controller).
    controller_utils.maybe_translate_local_file_mounts_and_sync_up(
        task, task_type='serve')
    yaml_path = os.path.join(_yaml_dir(), f'{service_name}.yaml')
    common_utils.dump_yaml(yaml_path, task.to_yaml_config())
    remote_yaml = f'~/.skytpu/serve/{service_name}.yaml'
    from skypilot_tpu.skylet import constants as skylet_constants  # pylint: disable=import-outside-toplevel
    controller_task = task_lib.Task(
        name=f'serve-daemon-{service_name}',
        run=(f'PYTHONPATH={skylet_constants.SKY_REMOTE_APP_DIR}'
             f':$PYTHONPATH {skylet_constants.SKY_PYTHON_CMD} '
             f'-m skypilot_tpu.serve.service '
             f'--service-name {service_name} '
             f'--register-from-yaml {remote_yaml}'),
        file_mounts={remote_yaml: yaml_path},
        envs={serve_constants.ENV_ON_CONTROLLER: '1'},
    )
    controller_task.set_resources(
        resources_lib.Resources(cpus='4+', memory='8+'))
    execution.launch(controller_task,
                     cluster_name=serve_constants.CONTROLLER_CLUSTER_NAME,
                     stream_logs=False, detach_run=True)
    endpoint = _wait_for_cluster_endpoint(service_name)
    if not detach:
        _wait_until_ready_on_cluster(service_name)
    return service_name, endpoint


def _wait_for_cluster_endpoint(service_name: str,
                               timeout: float = 120.0) -> str:
    from skypilot_tpu.serve import utils as serve_utils  # pylint: disable=import-outside-toplevel
    deadline = time.time() + timeout
    while time.time() < deadline:
        record = serve_utils.run_on_serve_controller(
            serve_utils.ServeCodeGen.get_service(service_name),
            'SERVE_RECORD:')
        if record and record.get('load_balancer_port'):
            host = serve_utils.controller_head_ip()
            return f'http://{host}:{record["load_balancer_port"]}'
        time.sleep(1.0)
    raise exceptions.SkyTpuError(
        f'Service {service_name} daemon did not come up on the '
        f'controller cluster in {timeout}s.')


def _wait_until_ready_on_cluster(service_name: str,
                                 timeout: float = 600.0) -> None:
    from skypilot_tpu.serve import utils as serve_utils  # pylint: disable=import-outside-toplevel
    deadline = time.time() + timeout
    while time.time() < deadline:
        record = serve_utils.run_on_serve_controller(
            serve_utils.ServeCodeGen.get_service(service_name),
            'SERVE_RECORD:')
        if record and record['status'] == ServiceStatus.READY.value:
            return
        time.sleep(1.0)
    raise exceptions.SkyTpuError(
        f'Service {service_name} not READY within {timeout}s.')


def update(task: task_lib.Task, service_name: str) -> int:
    """Install a new task/spec version; the controller rolls replicas
    over to it one at a time. Returns the new version."""
    _validate(task, service_name)
    from skypilot_tpu.serve import utils as serve_utils  # pylint: disable=import-outside-toplevel
    if serve_utils.controller_mode() == 'cluster':
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        from skypilot_tpu.serve import constants as serve_constants  # pylint: disable=import-outside-toplevel
        from skypilot_tpu.utils import controller_utils  # pylint: disable=import-outside-toplevel
        controller_utils.maybe_translate_local_file_mounts_and_sync_up(
            task, task_type='serve')
        yaml_path = os.path.join(_yaml_dir(), f'{service_name}.yaml')
        common_utils.dump_yaml(yaml_path, task.to_yaml_config())
        remote_yaml = f'~/.skytpu/serve/{service_name}.yaml'
        handle = backend_utils.check_cluster_available(
            serve_constants.CONTROLLER_CLUSTER_NAME)
        for runner in handle.get_command_runners()[:1]:
            runner.run(f'mkdir -p ~/.skytpu/serve', stream_logs=False)
            runner.rsync(yaml_path, remote_yaml, up=True,
                         stream_logs=False)
        return serve_utils.run_on_serve_controller(
            serve_utils.ServeCodeGen.update(service_name, remote_yaml),
            'SERVE_VERSION:')
    record = serve_state.get_service(service_name)
    if record is None:
        raise exceptions.InvalidTaskError(
            f'Service {service_name!r} does not exist; use serve.up().')
    yaml_path = os.path.join(
        _yaml_dir(), f'{service_name}.yaml')
    common_utils.dump_yaml(yaml_path, task.to_yaml_config())
    version = serve_state.update_service_spec(
        service_name, task.service.to_yaml_config(), yaml_path)
    # Nudge the controller (best effort; it also polls state).
    port = record.get('controller_port')
    if port:
        try:
            import requests  # pylint: disable=import-outside-toplevel
            requests.post(
                f'http://127.0.0.1:{port}'
                f'{http_protocol.CONTROLLER_UPDATE}',
                json={}, timeout=5)
        except Exception:  # pylint: disable=broad-except
            pass
    return version


def down(service_name: str, purge: bool = False) -> None:
    """Stop the daemon, terminate all replicas, remove state."""
    from skypilot_tpu.serve import utils as serve_utils  # pylint: disable=import-outside-toplevel
    if serve_utils.controller_mode() == 'cluster':
        try:
            result = serve_utils.run_if_controller_exists(
                serve_utils.ServeCodeGen.down(service_name, purge),
                'SERVE_DOWN:')
        except exceptions.SkyTpuError:
            if not purge:
                raise
            result = True  # best effort: controller unreachable
        if result is None and not purge:
            raise exceptions.InvalidTaskError(
                f'Service {service_name!r} does not exist (no serve '
                'controller cluster).')
        return
    record = serve_state.get_service(service_name)
    if record is None:
        if purge:
            return
        raise exceptions.InvalidTaskError(
            f'Service {service_name!r} does not exist.')
    serve_state.set_service_status(service_name,
                                   ServiceStatus.SHUTTING_DOWN)
    for pid_key in ('controller_pid', 'lb_pid'):
        pid = record.get(pid_key)
        if pid:
            try:
                os.killpg(os.getpgid(pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError, OSError):
                pass
    # Terminate replica clusters.
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    for replica in serve_state.get_replicas(service_name):
        try:
            core.down(replica['cluster_name'])
        except (exceptions.SkyTpuError, ValueError):
            if not purge:
                logger.warning(
                    f'failed to tear down {replica["cluster_name"]}')
    serve_state.remove_service(service_name)


def status(service_names: Optional[List[str]] = None
           ) -> List[Dict[str, Any]]:
    from skypilot_tpu.serve import utils as serve_utils  # pylint: disable=import-outside-toplevel
    if serve_utils.controller_mode() == 'cluster':
        return serve_utils.run_if_controller_exists(
            serve_utils.ServeCodeGen.status(service_names),
            'SERVE_STATUS:') or []
    records = serve_state.get_services()
    if service_names is not None:
        records = [r for r in records if r['name'] in service_names]
    for record in records:
        record['replicas'] = serve_state.get_replicas(record['name'])
    return records


def tail_logs(service_name: str, *, target: str = 'replica',
              replica_id: Optional[int] = None,
              follow: bool = False) -> None:
    """Print logs for a replica cluster (or the service daemon)."""
    record = serve_state.get_service(service_name)
    if record is None:
        raise exceptions.InvalidTaskError(
            f'Service {service_name!r} does not exist.')
    if target == 'replica':
        replicas = serve_state.get_replicas(service_name)
        if not replicas:
            raise exceptions.InvalidTaskError('No replicas yet.')
        if replica_id is None:
            replica_id = replicas[0]['replica_id']
        cluster = next(r['cluster_name'] for r in replicas
                       if r['replica_id'] == replica_id)
        from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
        core.tail_logs(cluster, follow=follow)
    else:
        log_path = os.path.join(_yaml_dir(), 'logs',
                                f'{service_name}.log')
        if os.path.exists(log_path):
            with open(log_path, encoding='utf-8',
                      errors='replace') as f:
                print(f.read(), end='')


# ------------------------------------------------------------------ util


def _start_daemon(service_name: str) -> None:
    log_dir = common_utils.ensure_dir(os.path.join(_yaml_dir(), 'logs'))
    log_path = os.path.join(log_dir, f'{service_name}.log')
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(  # pylint: disable=consider-using-with
            [sys.executable, '-m', 'skypilot_tpu.serve.service',
             '--service-name', service_name],
            stdout=log_f, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, start_new_session=True)
    from skypilot_tpu.utils import daemon_registry  # pylint: disable=import-outside-toplevel
    daemon_registry.register(proc.pid, 'serve-daemon')
    serve_state.set_service_pids(service_name, controller_pid=proc.pid,
                                 lb_pid=proc.pid)


def _wait_for_endpoint(service_name: str, timeout: float = 60.0) -> str:
    deadline = time.time() + timeout
    while time.time() < deadline:
        record = serve_state.get_service(service_name)
        if record and record.get('load_balancer_port'):
            return f'http://127.0.0.1:{record["load_balancer_port"]}'
        time.sleep(0.3)
    raise exceptions.SkyTpuError(
        f'Service {service_name} daemon did not come up in {timeout}s '
        f'(see {_yaml_dir()}/logs/{service_name}.log).')


def _wait_until_ready(service_name: str, timeout: float = 600.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        record = serve_state.get_service(service_name)
        if record and record['status'] == ServiceStatus.READY.value:
            return
        time.sleep(1.0)
    raise exceptions.SkyTpuError(
        f'Service {service_name} not READY within {timeout}s.')
