"""Replica roles: the single home for role names and the
missing-role default.

Every plane that reads a replica record used to spell the default
inline (`r.get('role') or 'mixed'` — replica_managers' ready set,
drain-sibling pick and load view, the router's endpoints, the
controller's scrape targets, the CLI tables).  One stale copy is a
routing bug: a record without a role must mean *mixed* everywhere or
a morphed/legacy replica lands in the wrong pool.  This module is
deliberately a leaf (no serve imports) so every layer can use it.
"""
from __future__ import annotations

from typing import Any, Mapping, Optional

ROLES = ('prefill', 'decode', 'mixed')
DEFAULT_ROLE = 'mixed'

# Launch-time prefill share per static role (scheduler.RoleBudget
# derives per-tick budgets from these; 0.5 = unclamped mixed).
DEFAULT_SPLITS = {'prefill': 1.0, 'decode': 0.0, 'mixed': 0.5}


def normalize(role: Optional[str]) -> str:
    """A possibly-missing role value -> a valid role name (None/''
    -> the mixed default).  Unknown names raise: silently coercing a
    typo to 'mixed' would hide a misrouted pool."""
    if not role:
        return DEFAULT_ROLE
    if role not in ROLES:
        raise ValueError(f'Unknown replica role {role!r}; '
                         f'one of {ROLES}')
    return role


def role_of(record: Mapping[str, Any]) -> str:
    """The role of a replica record/info dict, defaulting missing or
    empty values to 'mixed' (pre-roles rows and user containers that
    never advertise one)."""
    return normalize(record.get('role'))
