"""Request lifecycle + bounded admission queue for the batching engine.

Split out of `serve/batching_engine.py` (which remains the facade and
re-exports every public name here): this module owns everything about a
request BEFORE it reaches a KV slot and AFTER tokens start flowing —

- :class:`Request` — the handle `submit()` returns: token stream with
  replaying watchers, result()/stream()/cancel(), idempotent finish
  (worker-finish vs stop() vs submit-after-stop races resolve to one
  winner), per-request :class:`~..observability.tracing.RequestSpan`.
- :class:`AdmissionQueue` — bounded FIFO with TTL: `max_queue` rejects
  new submits (:class:`QueueFull` -> HTTP 429 + Retry-After) and
  `queue_ttl` expires stale waiters (:class:`QueueExpired` -> 503), so
  a load spike degrades with fast honest rejections instead of
  unbounded TTFT.  The queue records admission waits into the
  histogram only when a request actually lands in a slot — a deferred
  pop (page pool exhausted) goes back to the FRONT uncounted.
- :class:`Slot` / :class:`PendingPrefill` — per-slot host bookkeeping.
- :class:`RoleBudget` — per-tick prefill/decode token budgets: the
  replica's role expressed as a *fraction* instead of a static launch
  property.  The engine's chunked-prefill interleave clamps each
  tick's prefill chunk to the prefill budget, and the smooth-WRR
  admission stops admitting new decode slots past the decode budget —
  a decode-heavy budget starves prefill gracefully mid-prompt rather
  than blocking a tick, and the controller can swap the whole budget
  in place (live role morph) without restarting the engine.
"""
from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional

from skypilot_tpu.serve import roles as roles_lib

from skypilot_tpu.observability import metrics as metrics_lib
from skypilot_tpu.observability import tracing
from skypilot_tpu.serve import qos as qos_lib

# Queue-wait histogram bucket upper bounds (seconds); the last bucket
# is open-ended.  Surfaced via stats() -> /health for autoscaling.
WAIT_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

_M_ADMITTED = metrics_lib.counter(
    'skytpu_engine_admitted_total',
    'Requests admitted into a KV slot.')
_M_REJECTED = metrics_lib.counter(
    'skytpu_engine_rejected_total',
    'Requests rejected at admission, by reason.', ('reason',))
_M_QUEUE_DEPTH = metrics_lib.gauge(
    'skytpu_engine_queue_depth', 'Requests waiting for a slot.')
_M_QUEUE_WAIT = metrics_lib.histogram(
    'skytpu_engine_queue_wait_seconds',
    'Seconds a request waited queued before admission.',
    buckets=WAIT_BUCKETS)
_M_TTFT = metrics_lib.histogram(
    'skytpu_engine_ttft_seconds',
    'Submit-to-first-token latency per request.')
_M_ITL = metrics_lib.histogram(
    'skytpu_engine_itl_seconds',
    'Inter-token gaps during decode.',
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0))
_M_QOS_ADMITTED = metrics_lib.counter(
    'skytpu_engine_qos_admitted_total',
    'Requests admitted into a KV slot, by QoS class.', ('qos_class',))
_M_PREFILL_BUDGET = metrics_lib.gauge(
    'skytpu_engine_prefill_budget_tokens',
    'Per-tick prefill token budget in force (fractional role; set on '
    'every budget swap).')
_M_DECODE_BUDGET = metrics_lib.gauge(
    'skytpu_engine_decode_budget_tokens',
    'Per-tick decode token budget in force (caps concurrent decode '
    'slots; set on every budget swap).')
_M_BUDGET_SWAPS = metrics_lib.counter(
    'skytpu_engine_budget_swaps_total',
    'Role-budget swaps applied (controller rebalance pushes + live '
    'role morphs).')


class QueueFull(RuntimeError):
    """submit() rejected: the admission queue is at max_queue, or the
    KV page pool cannot cover the request while a backlog waits.

    `retry_after` is the engine's estimate (seconds) of when a slot's
    worth of backlog will have drained — servers surface it as an HTTP
    Retry-After header on the 429.
    """

    def __init__(self, message: str, retry_after: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after = max(1.0, retry_after)


class QueueExpired(RuntimeError):
    """The request sat queued past queue_ttl and was never admitted
    (servers map this to 503 + Retry-After)."""

    def __init__(self, message: str, retry_after: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after = max(1.0, retry_after)


class DeadlineExceeded(RuntimeError):
    """The request's deadline (X-SkyTPU-Deadline-Ms) passed before it
    finished: queued requests expire at pop, decoding requests are
    reaped mid-generation — either way the slot and its KV pages are
    freed instead of decoding for a client that stopped waiting.
    Servers map this to HTTP 504."""


class Request:

    def __init__(self, prompt_ids: List[int], max_new_tokens: int,
                 stop_token, temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0,
                 request_id: Optional[str] = None,
                 route_meta: Optional[Dict[str, Any]] = None,
                 deadline_ms: Optional[float] = None,
                 qos_class: Optional[str] = None) -> None:
        self.prompt_ids = list(prompt_ids)
        # QoS class (X-SkyTPU-QoS-Class, stamped by the router): the
        # class's token budget clamps max_new_tokens and its deadline
        # default applies when the request carries no deadline of its
        # own (an explicit client deadline always wins).
        self.qos_class = qos_lib.normalize(qos_class)
        qos_spec = qos_lib.engine_config().get(self.qos_class)
        if qos_spec is not None:
            if qos_spec.max_new_tokens is not None:
                max_new_tokens = min(int(max_new_tokens),
                                     qos_spec.max_new_tokens)
            if deadline_ms is None and qos_spec.deadline_ms is not None:
                deadline_ms = qos_spec.deadline_ms
        self.max_new_tokens = max_new_tokens
        # Per-request phase trace (queue/prefill/TTFT/ITL/total); the
        # id arrives via X-SkyTPU-Request-Id or is generated here.
        self.span = tracing.RequestSpan(request_id)
        self.request_id = self.span.request_id
        if route_meta:
            # Routing facts the LB forwarded (X-SkyTPU-Routed-Role /
            # -Affinity / -Handoff-Ms): stamped into the span so "why
            # was THIS request slow" includes how it was routed.
            self.span.routed_role = route_meta.get('routed_role')
            self.span.affinity_hit = route_meta.get('affinity_hit')
            self.span.handoff_ms = route_meta.get('handoff_ms')
            # X-SkyTPU-Attempt: disambiguates this span from the
            # other replica's when the LB's one-shot retry reused the
            # request id (trace assembly shows both legs).
            self.span.attempt = route_meta.get('attempt')
        # stop_token: None, a single id, or any iterable of ids (the
        # tokenizer's multi-EOS stop set — instruct checkpoints stop at
        # chat turn-end markers, not just the model-level EOS).
        if stop_token is None:
            self.stop_ids = frozenset()
        elif isinstance(stop_token, int):
            self.stop_ids = frozenset({stop_token})
        else:
            self.stop_ids = frozenset(int(t) for t in stop_token)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.submit_time = time.monotonic()
        # Absolute monotonic deadline (None = no deadline): after it,
        # the engine cancels the slot and frees its pages instead of
        # decoding to a client that stopped waiting.
        self.deadline: Optional[float] = (
            self.submit_time + float(deadline_ms) / 1e3
            if deadline_ms is not None else None)
        self.done = threading.Event()
        self.tokens: List[int] = []
        self.error: Optional[Exception] = None
        self.cancelled = False
        # Streaming consumers read tokens as they are produced; the
        # None sentinel marks the end of the stream.
        self._live: 'queue.Queue[Optional[int]]' = queue.Queue()
        # _finish can race (worker finishing vs stop() failing-fast vs
        # submit() losing the stop race): first caller wins, later
        # calls are no-ops — otherwise two None sentinels truncate a
        # stream() and a success can be overwritten with an error.
        self._state_lock = threading.Lock()
        # Event-loop bridges (serve/async_server.py): called with each
        # token and a final None, from the engine worker thread, under
        # the state lock — watchers must be cheap and non-blocking
        # (call_soon_threadsafe qualifies).
        self._watchers: List[Any] = []
        # Set by the engine at submit(): finished spans land here.
        self._span_store: Optional[tracing.SpanStore] = None

    def add_watcher(self, fn) -> None:
        """Subscribe fn(token|None) to this request's token stream;
        tokens already produced are replayed first, so late subscribers
        never miss a prefix (the admission path can push the first
        token before the caller gets the request handle back)."""
        with self._state_lock:
            for token in self.tokens:
                fn(token)
            if self.done.is_set():
                fn(None)
            else:
                self._watchers.append(fn)

    def _push(self, token: int) -> None:
        with self._state_lock:
            if self.done.is_set():
                # stop() already finished this request; a worker still
                # mid-tick must not append past the sentinel.
                return
            gap = self.span.mark_token()
            if gap is None:
                if self.span.ttft_s is not None:
                    _M_TTFT.observe(self.span.ttft_s)
            else:
                _M_ITL.observe(gap)
            self.tokens.append(token)
            self._live.put(token)
            self._notify(token)

    def _finish(self, error: Optional[Exception] = None) -> None:
        with self._state_lock:
            if self.done.is_set():
                return
            self.error = error
            self.done.set()
            if error is not None:
                status = type(error).__name__
            elif self.cancelled:
                status = 'cancelled'
            else:
                status = 'ok'
            self.span.finish(status)
            if self._span_store is not None:
                self._span_store.add(self.span)
            self._live.put(None)
            self._notify(None)
            self._watchers.clear()

    def _notify(self, token: Optional[int]) -> None:
        # A raising watcher (e.g. call_soon_threadsafe on a closed
        # event loop at shutdown) must not propagate into the engine
        # worker — that would fail the WHOLE engine for one dead
        # subscriber.  Drop it instead.
        for fn in list(self._watchers):
            try:
                fn(token)
            except Exception:  # pylint: disable=broad-except
                try:
                    self._watchers.remove(fn)
                except ValueError:
                    pass

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError('generation timed out')
        if self.error is not None:
            raise self.error
        return self.tokens

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as the engine produces them."""
        while True:
            token = self._live.get(timeout=timeout)
            if token is None:
                if self.error is not None:
                    raise self.error
                return
            yield token

    def cancel(self) -> None:
        """Stop generating for this request (client went away); the
        engine frees the slot on its next tick."""
        self.cancelled = True

    def deadline_exceeded(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None and
                (time.monotonic() if now is None else now) >
                self.deadline)


class Slot:

    def __init__(self) -> None:
        self.request: Optional[Request] = None
        self.next_token = 0          # legacy (unpipelined) loop only
        self.drafter = None          # NgramDrafter when spec decoding

    @property
    def active(self) -> bool:
        return self.request is not None


class PendingPrefill:
    """A dense prompt mid-chunked-prefill: the slot is reserved but
    does not join decode ticks until every chunk has run."""

    def __init__(self, slot_id: int, request: Request,
                 n_target: int) -> None:
        self.slot_id = slot_id
        self.request = request
        self.n_target = n_target     # tokens to prefill (n-1, dense)
        self.consumed = 0
        self.cache: Optional[Dict[str, Any]] = None  # private [*,1,..]
        # Paged mode: the cache_manager.AdmissionPlan holding this
        # request's pages (reuse + fresh) until activation/abandon.
        self.plan: Optional[Any] = None


@dataclasses.dataclass
class RoleBudget:
    """Per-tick token budgets that make replica role fractional.

    ``prefill_tokens`` caps the prompt tokens a tick's chunked-prefill
    advance may consume; ``decode_tokens`` caps the decode tokens a
    tick may spend, which — at one token per busy slot per tick — is a
    cap on *concurrent decode slots* enforced at admission (running
    decodes always finish; a shrunk decode budget bites as slots
    free).  Both floors at 1: budgets throttle, they never deadlock —
    a starved phase still makes one token of progress per tick, so a
    mid-prompt prefill crawls rather than wedges.

    ``split`` is the prefill share the budget was derived from (the
    controller's rebalance unit); ``version`` orders controller pushes
    so a stale rebalance can never overwrite a newer one.
    """
    prefill_tokens: int
    decode_tokens: int
    role: str = roles_lib.DEFAULT_ROLE
    split: float = 0.5
    version: int = 0

    def __post_init__(self) -> None:
        self.prefill_tokens = max(1, int(self.prefill_tokens))
        self.decode_tokens = max(1, int(self.decode_tokens))
        self.split = min(1.0, max(0.0, float(self.split)))
        self.version = int(self.version)
        if self.role not in roles_lib.ROLES:
            raise ValueError(f'Unknown role {self.role!r}; one of '
                             f'{roles_lib.ROLES}')

    @classmethod
    def from_split(cls, split: float, *, slots: int,
                   prefill_chunk: int,
                   role: str = roles_lib.DEFAULT_ROLE,
                   version: int = 0) -> 'RoleBudget':
        """Budget from a prefill share in [0, 1].  At 0.5 both phases
        run unclamped (byte-identical to the pre-budget engine — the
        mixed default costs nothing); pushing the split toward either
        end linearly starves the other phase down to its 1-token
        liveness floor."""
        split = min(1.0, max(0.0, float(split)))
        return cls(
            prefill_tokens=round(prefill_chunk * min(1.0, 2 * split)),
            decode_tokens=round(slots * min(1.0, 2 * (1 - split))),
            role=role, split=split, version=version)

    @classmethod
    def for_role(cls, role: str, *, slots: int, prefill_chunk: int,
                 version: int = 0) -> 'RoleBudget':
        """The launch-time profile of a static role pool: prefill
        replicas spend their ticks prefilling (decode floor), decode
        replicas the reverse, mixed replicas are unclamped."""
        return cls.from_split(roles_lib.DEFAULT_SPLITS[role],
                              slots=slots, prefill_chunk=prefill_chunk,
                              role=role, version=version)

    def as_dict(self) -> Dict[str, Any]:
        return {'role': self.role, 'split': self.split,
                'prefill_tokens': self.prefill_tokens,
                'decode_tokens': self.decode_tokens,
                'version': self.version}


class AdmissionQueue:
    """Bounded, TTL'd FIFO between submit() threads and the worker."""

    def __init__(self, max_queue: int = 0,
                 queue_ttl: Optional[float] = None,
                 drain_estimate: Callable[[], float] = lambda: 1.0
                 ) -> None:
        self.max_queue = int(max_queue)      # 0 = unbounded
        self.queue_ttl = queue_ttl           # None = no expiry
        self._drain_estimate = drain_estimate
        self._queue: Deque[Request] = collections.deque()
        # Per-tick role budget (None = unclamped, the pre-budget
        # behavior).  Swapped atomically under the condition lock by
        # set_role_budget (controller rebalance push / live morph);
        # admission_allowed gates new decode slots against it.
        self.role_budget: Optional[RoleBudget] = None
        self.budget_swaps = 0
        # Smooth weighted round-robin credits per QoS class: when BOTH
        # classes have queued work, pops interleave by class weight
        # (interactive's floor under a batch backlog and vice versa);
        # single-class queues stay strictly FIFO.
        self._wrr_credit: Dict[str, int] = {}
        self.cond = threading.Condition()
        # Engine-local metric mirror (stats()); the process-global
        # registry instruments above carry the /metrics view.
        self._metrics_lock = threading.Lock()
        self.queue_full_rejections = 0
        self.queue_ttl_expiries = 0
        self.wait_hist = [0] * (len(WAIT_BUCKETS) + 1)
        _M_QUEUE_DEPTH.set(0)

    def __len__(self) -> int:
        with self.cond:
            return len(self._queue)

    def submit(self, request: Request) -> None:
        """Append (FIFO) or reject with QueueFull at the bound."""
        with self.cond:
            if self.max_queue and len(self._queue) >= self.max_queue:
                with self._metrics_lock:
                    self.queue_full_rejections += 1
                _M_REJECTED.labels(reason='queue_full').inc()
                raise QueueFull(
                    f'admission queue full ({self.max_queue} waiting); '
                    'retry later', retry_after=self._drain_estimate())
            self._queue.append(request)
            _M_QUEUE_DEPTH.set(len(self._queue))
            self.cond.notify()

    def set_role_budget(self, budget: Optional[RoleBudget]) -> bool:
        """Install a new per-tick budget (None = unclamped).  Stale
        pushes lose: a budget older than the one in force is dropped
        (version-ordered), so a slow rebalance POST can never undo a
        newer morph.  Returns whether the swap was applied."""
        with self.cond:
            current = self.role_budget
            if (budget is not None and current is not None and
                    budget.version < current.version):
                return False
            self.role_budget = budget
            self.budget_swaps += 1
            self.cond.notify_all()
        _M_BUDGET_SWAPS.inc()
        if budget is not None:
            _M_PREFILL_BUDGET.set(budget.prefill_tokens)
            _M_DECODE_BUDGET.set(budget.decode_tokens)
        return True

    def admission_allowed(self, busy_slots: int) -> bool:
        """May this tick admit one more decode slot?  The decode-token
        budget is a concurrency cap: each busy slot spends one decode
        token per tick, so admission stops once the busy count reaches
        the budget — queued requests wait (smooth-WRR order preserved)
        until the budget flips back or a slot frees."""
        budget = self.role_budget
        return budget is None or busy_slots < budget.decode_tokens

    def prefill_tokens_per_tick(self, default: int) -> int:
        """Per-tick prompt-token allowance for chunked prefill
        (`default` = the configured chunk size when unclamped)."""
        budget = self.role_budget
        if budget is None:
            return default
        return min(default, budget.prefill_tokens)

    def reject(self, reason: str, message: str) -> QueueFull:
        """Count a non-queue-bound rejection (e.g. page-pool
        exhaustion) and build the QueueFull to raise."""
        with self._metrics_lock:
            self.queue_full_rejections += 1
        _M_REJECTED.labels(reason=reason).inc()
        return QueueFull(message, retry_after=self._drain_estimate())

    def requeue_front(self, request: Request) -> None:
        """Put a popped-but-not-admitted request back at the head
        (admission deferred: no pages/slots right now); its queue-wait
        keeps accruing and is recorded only at the real admission."""
        with self.cond:
            self._queue.appendleft(request)
            _M_QUEUE_DEPTH.set(len(self._queue))

    def _pop_index_locked(self) -> int:
        """Index of the next request to pop: FIFO within a class;
        across classes, smooth weighted round-robin by QoS weight
        (call with self.cond held)."""
        first_of: Dict[str, int] = {}
        for idx, request in enumerate(self._queue):
            cls = getattr(request, 'qos_class', None) or \
                qos_lib.default_class()
            if cls not in first_of:
                first_of[cls] = idx
        if len(first_of) <= 1:
            return 0
        specs = qos_lib.engine_config()
        total = 0
        for cls in first_of:
            weight = specs[cls].weight if cls in specs else 1
            self._wrr_credit[cls] = \
                self._wrr_credit.get(cls, 0) + weight
            total += weight
        chosen = max(first_of,
                     key=lambda c: (self._wrr_credit.get(c, 0), c))
        self._wrr_credit[chosen] -= total
        return first_of[chosen]

    def pop(self) -> Optional[Request]:
        """Pop the next live queued request, expiring stale ones.  Does
        NOT record the admission — call record_admission() once the
        request actually lands in a slot."""
        while True:
            with self.cond:
                if not self._queue:
                    return None
                index = self._pop_index_locked()
                if index == 0:
                    request = self._queue.popleft()
                else:
                    request = self._queue[index]
                    del self._queue[index]
                _M_QUEUE_DEPTH.set(len(self._queue))
            if request.cancelled:
                request._finish()  # pylint: disable=protected-access
                continue
            if request.deadline_exceeded():
                _M_REJECTED.labels(reason='deadline_exceeded').inc()
                request._finish(DeadlineExceeded(  # pylint: disable=protected-access
                    'request deadline passed while queued'))
                continue
            if (self.queue_ttl is not None and
                    time.monotonic() - request.submit_time >
                    self.queue_ttl):
                self._record_expiry(1)
                request._finish(QueueExpired(  # pylint: disable=protected-access
                    f'request expired after {self.queue_ttl}s queued',
                    retry_after=self._drain_estimate()))
                continue
            return request

    def record_admission(self, request: Request) -> None:
        request.span.mark_admitted()
        wait = time.monotonic() - request.submit_time
        _M_ADMITTED.inc()
        _M_QOS_ADMITTED.labels(
            qos_class=getattr(request, 'qos_class', None) or
            qos_lib.default_class()).inc()
        _M_QUEUE_WAIT.observe(wait)
        with self._metrics_lock:
            for i, bound in enumerate(WAIT_BUCKETS):
                if wait < bound:
                    self.wait_hist[i] += 1
                    return
            self.wait_hist[-1] += 1

    def _record_expiry(self, n: int) -> None:
        with self._metrics_lock:
            self.queue_ttl_expiries += n
        _M_REJECTED.labels(reason='queue_expired').inc(n)

    def expire_stale(self) -> None:
        """Fail requests that outlived queue_ttl (or their own
        deadline) while still queued — without this a saturated engine
        leaves them waiting out their whole client timeout."""
        now = time.monotonic()
        expired = []
        deadlined = []
        with self.cond:
            if not self._queue:
                return
            keep: Deque[Request] = collections.deque()
            for request in self._queue:
                if request.deadline_exceeded(now):
                    deadlined.append(request)
                elif (self.queue_ttl is not None and
                        now - request.submit_time > self.queue_ttl):
                    expired.append(request)
                else:
                    keep.append(request)
            self._queue = keep
            _M_QUEUE_DEPTH.set(len(keep))
        if expired:
            self._record_expiry(len(expired))
        for request in expired:
            request._finish(QueueExpired(  # pylint: disable=protected-access
                f'request expired after {self.queue_ttl}s queued',
                retry_after=self._drain_estimate()))
        if deadlined:
            _M_REJECTED.labels(reason='deadline_exceeded').inc(
                len(deadlined))
        for request in deadlined:
            request._finish(DeadlineExceeded(  # pylint: disable=protected-access
                'request deadline passed while queued'))

    def drain(self, error_factory: Callable[[], Exception]) -> None:
        """Fail everything still queued (shutdown/engine failure)."""
        while True:
            with self.cond:
                if not self._queue:
                    _M_QUEUE_DEPTH.set(0)
                    return
                request = self._queue.popleft()
            request._finish(error_factory())  # pylint: disable=protected-access

    def stats(self) -> Dict[str, Any]:
        hist = {}
        with self._metrics_lock:
            for i, bound in enumerate(WAIT_BUCKETS):
                hist[f'<{bound}s'] = self.wait_hist[i]
            hist[f'>={WAIT_BUCKETS[-1]}s'] = self.wait_hist[-1]
            return {
                'queued_requests': len(self._queue),
                'queue_full_rejections': self.queue_full_rejections,
                'queue_ttl_expiries': self.queue_ttl_expiries,
                'queue_wait_hist': hist,
                'max_queue': self.max_queue,
                'role_budget': (self.role_budget.as_dict()
                                if self.role_budget is not None
                                else None),
                'budget_swaps': self.budget_swaps,
            }
