"""Slice-serving runtime: one replica = one gang-scheduled multi-host
slice.

ROADMAP item 3, the last pillar of the serving story.  Training
already treats a TPU pod slice as the unit of compute (gang supervisor,
`parallel/mesh.py`, fsdp/tp sharding); serving replicas were single
processes.  This module makes "replica" mean "slice":

- **Mesh.**  `build_slice_mesh(num_hosts, cfg)` lays the slice out as
  `sequence x tensor` over its hosts (emulated hosts = one virtual
  device each; real hosts contribute their local chips).  The tensor
  factor takes as many hosts as the config's head/ff/vocab counts
  divide — weights shard per `parallel/sharding.py`'s SpecLayout
  (heads/mlp/vocab on 'tensor', embed on 'fsdp'), so a model too big
  for one host spreads across the slice; the remainder lands on
  'sequence' for long-context prefill.  The paged KV pool shards
  through the existing `page_pool_sharding` (kv heads on 'tensor').
- **Gang.**  :class:`SliceReplicaEngine` wraps the continuous-batching
  engine with a rank protocol (`serve/coordinator.py`): rank 0 owns
  the HTTP front (the LB keeps talking to ONE url) and broadcasts
  every host-side scheduling decision — admit, prefill, tick — so all
  ranks dispatch identical SPMD steps.  One dead rank fails the
  replica AS A UNIT: the engine fails everything in flight, `/health`
  turns 503 with ``slice.degraded``, the controller retires and
  replaces the replica, and the LB re-routes to survivors (chaos
  scenario ``replica_rank_death`` proves zero lost requests).
- **Sequence-parallel prefill.**  Prompts at/above ``sp_threshold``
  tokens skip the chunked-prefill ladder and run ONE
  `models/decode.prefill_sp` shot: ring attention
  (`ops/ring_attention.py`) splits the quadratic attention and its
  activations across the slice's sequence axis, so a 100k-token
  context that would OOM (or stall) one host prefills in ~1/hosts the
  time (bench_serve.py `sp_prefill` pins the scaling).

Emulated vs real:

- *Emulated* (tests, CPU bench): all `num_hosts` virtual devices live
  in this process (`xla_force_host_platform_device_count`); follower
  ranks are `LocalRank` threads that execute the command log (and its
  `serve.rank_exec` chaos site) while rank 0's dispatch covers every
  device.
- *Real slices*: each TPU-VM worker runs ``python -m
  skypilot_tpu.serve.slice_replica`` under the gang supervisor.
  Rank 0 (`SKYTPU_HOST_RANK=0`) initializes `jax.distributed`, accepts
  follower connections on the coordinator port, and serves HTTP; ranks
  > 0 connect and execute each broadcast command by dispatching the
  same jitted step on their local devices (`follower_serve`).
"""
from __future__ import annotations

import argparse
import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import batching_engine as batching_engine_lib
from skypilot_tpu.serve import coordinator as coordinator_lib

logger = sky_logging.init_logger(__name__)

# Port offset from the JAX coordinator for the serve rank protocol
# (real slices; the gang env contract pins the jax.distributed port).
SLICE_COORD_PORT_OFFSET = 17


def sp_threshold_default() -> int:
    """Prompt tokens at which a slice replica prefills sequence-
    parallel instead of chunked (env SKYTPU_SLICE_SP_THRESHOLD)."""
    return int(os.environ.get('SKYTPU_SLICE_SP_THRESHOLD', '1024'))


def slice_axes(num_hosts: int, cfg,
               tensor: Optional[int] = None,
               sequence: Optional[int] = None) -> Dict[str, int]:
    """Factor a slice's hosts into (sequence, tensor) mesh axes.

    Default policy: tensor takes the LARGEST divisor of num_hosts the
    config's shapes support (n_heads, n_kv_heads, d_ff, vocab_size all
    divisible) — weight sharding is why the model needs a slice at all
    — and the remainder rides 'sequence' for long-context prefill.
    Either factor can be pinned explicitly (``--slice-sequence`` /
    ``--slice-tensor``); they must multiply to num_hosts.
    """
    if num_hosts < 1:
        raise ValueError(f'num_hosts must be >= 1, got {num_hosts}')
    if tensor is not None and sequence is not None:
        if tensor * sequence != num_hosts:
            raise ValueError(
                f'sequence ({sequence}) x tensor ({tensor}) must equal '
                f'num_hosts ({num_hosts})')
        return {'sequence': int(sequence), 'tensor': int(tensor)}
    if sequence is not None:
        if num_hosts % sequence:
            raise ValueError(f'sequence ({sequence}) must divide '
                             f'num_hosts ({num_hosts})')
        return {'sequence': int(sequence),
                'tensor': num_hosts // int(sequence)}
    if tensor is None:
        tensor = 1
        for d in range(1, num_hosts + 1):
            if num_hosts % d:
                continue
            if (cfg.n_heads % d or cfg.n_kv_heads % d or
                    cfg.d_ff % d or cfg.vocab_size % d):
                continue
            tensor = d
    if num_hosts % tensor:
        raise ValueError(f'tensor ({tensor}) must divide num_hosts '
                         f'({num_hosts})')
    for dim, value in (('n_heads', cfg.n_heads),
                       ('n_kv_heads', cfg.n_kv_heads),
                       ('d_ff', cfg.d_ff),
                       ('vocab_size', cfg.vocab_size)):
        if value % tensor:
            raise ValueError(
                f'tensor={tensor} must divide {dim} ({value}); pin '
                f'--slice-sequence to keep more hosts on the sequence '
                f'axis')
    return {'sequence': num_hosts // int(tensor), 'tensor': int(tensor)}


def build_slice_mesh(num_hosts: int, cfg, *, devices=None,
                     tensor: Optional[int] = None,
                     sequence: Optional[int] = None):
    """jax.sharding.Mesh for one slice replica: `sequence x tensor`
    over the slice's devices (emulated host = one virtual device)."""
    import jax  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.parallel import mesh as mesh_lib  # pylint: disable=import-outside-toplevel
    axes = slice_axes(num_hosts, cfg, tensor=tensor, sequence=sequence)
    if devices is None:
        devices = jax.devices()
    if len(devices) < num_hosts:
        raise ValueError(
            f'num_hosts={num_hosts} needs {num_hosts} devices; have '
            f'{len(devices)} (emulated hosts ride '
            f'xla_force_host_platform_device_count on CPU)')
    return mesh_lib.build_mesh(
        mesh_lib.MeshConfig(sequence=axes['sequence'],
                            tensor=axes['tensor']),
        devices=devices[:num_hosts])


class SliceReplicaEngine(batching_engine_lib.ContinuousBatchingEngine):
    """Continuous-batching engine whose replica is a multi-host slice.

    Extends the base engine with (a) the slice mesh — weights, KV pool
    and engine state land sharded/replicated per parallel/sharding.py;
    (b) the rank protocol — every tick/admission broadcasts through the
    SliceCoordinator before the SPMD dispatch, and a dead rank fails
    the replica as a unit; (c) sequence-parallel prefill for prompts at
    or above `sp_threshold` tokens."""

    def __init__(self, cfg, params, *, num_hosts: int,
                 sp_threshold: Optional[int] = None,
                 sequence: Optional[int] = None,
                 tensor: Optional[int] = None,
                 mesh=None,
                 rank_channels: Optional[List[Any]] = None,
                 **kwargs) -> None:
        import functools  # pylint: disable=import-outside-toplevel

        import jax  # pylint: disable=import-outside-toplevel

        from skypilot_tpu.models import decode  # pylint: disable=import-outside-toplevel
        self.num_hosts = int(num_hosts)
        self.sp_threshold = (sp_threshold_default()
                             if sp_threshold is None
                             else int(sp_threshold))
        if mesh is None:
            mesh = build_slice_mesh(self.num_hosts, cfg,
                                    sequence=sequence, tensor=tensor)
        self._slice_mesh = mesh
        self._sp_degree = int(mesh.shape.get('sequence', 1))
        self._coordinator = coordinator_lib.SliceCoordinator(
            self.num_hosts, channels=rank_channels)
        self._sp_prefills = 0
        # One compile per padded prompt width (the bucket ladder bounds
        # the count, same as the chunked path).
        self._sp_prefill_jit = jax.jit(functools.partial(
            decode.prefill_sp, cfg, mesh=mesh,
            max_len=kwargs.get('max_len', 512)))
        super().__init__(cfg, params, mesh=mesh, **kwargs)
        # The SP prefill entry is created before the base engine builds
        # the recompile sentinel; enroll it now.
        self._sp_prefill_jit = self._sentinel.wrap('sp_prefill',
                                                   self._sp_prefill_jit)

    # --------------------------------------------------- gang protocol

    def _dispatch_step(self):
        """Coordinated tick: rank 0 broadcasts TICK and waits for every
        rank's ack (the `slice_sync_ms` overhead), then dispatches the
        SPMD step.  RankDead propagates to the worker loop, which fails
        the replica as a unit — a half-dead slice must never keep
        half-serving."""
        self._coordinator.tick()
        self._profiler.lap('slice-sync')
        return super()._dispatch_step()

    def _dispatch_spec_step(self, drafts):
        """Coordinated speculative verify tick: the draft batch rides
        the TICK payload so real followers (`FollowerExecutor`) dispatch
        the identical spec step — drafts are rank 0's host-side
        decision, exactly like admissions."""
        import numpy as np  # pylint: disable=import-outside-toplevel
        self._coordinator.broadcast(
            coordinator_lib.CMD_TICK,
            spec=np.asarray(drafts).tolist())
        self._profiler.lap('slice-sync')
        return super()._dispatch_spec_step(drafts)

    def _activate(self, slot_id, request, token, length, *,
                  remaining, key) -> None:
        """Slot activation broadcasts the FULL admission so follower
        ranks can mirror it against their local shard: the prompt (the
        follower re-runs the prefill — on real hardware each host must
        compute its shard of every step anyway), the page row rank 0's
        planner allocated, and the per-slot decode state (token,
        budget, stop set, key chain seed, sampling params)."""
        import numpy as np  # pylint: disable=import-outside-toplevel
        row = (self._kv.slot_row(slot_id)
               if self._kv is not None else None)
        self._coordinator.broadcast(
            coordinator_lib.CMD_ADMIT, slot=slot_id,
            tokens=len(request.prompt_ids),
            prompt=[int(t) for t in request.prompt_ids],
            length=int(length), token=int(token),
            remaining=int(remaining),
            stop_ids=sorted(int(s) for s in request.stop_ids),
            key=np.asarray(key).tolist(),
            temperature=float(request.temperature),
            top_k=int(request.top_k), row=row,
            request_id=request.request_id)
        request.span.slice_sync_ms = round(
            self._coordinator.sync_ms_mean(), 4)
        super()._activate(slot_id, request, token, length,
                          remaining=remaining, key=key)

    def _release_slot_pages(self, slot_id) -> None:
        """Slot release is a coordinated command too: followers park
        the slot's block table on the null page exactly when rank 0
        does, so stale in-flight writes land in garbage on EVERY
        host."""
        if self._kv is not None:
            self._coordinator.broadcast(
                coordinator_lib.CMD_RELEASE, slot=slot_id)
        super()._release_slot_pages(slot_id)

    # ------------------------------------------------------ SP prefill

    def _sp_padded_width(self, n_target: int) -> Optional[int]:
        """Padded prompt width for the one-shot SP prefill: the bucket
        of n_target, rounded up to a multiple of the sequence degree,
        capped at max_len.  None = does not fit; use the chunked
        path."""
        sp = self._sp_degree
        width = min(self._bucket(n_target), self.max_len)
        width = -(-width // sp) * sp
        if width > self.max_len:
            width = -(-n_target // sp) * sp
        if width > self.max_len:
            return None
        return width

    def _try_sp_prefill(self, prompt_ids: List[int],
                        n_target: int) -> Optional[Dict[str, Any]]:
        """One-shot sequence-parallel prefill of [0, n_target), or None
        when the prompt should take the chunked path (below threshold,
        MoE, or padding does not fit)."""
        import numpy as np  # pylint: disable=import-outside-toplevel
        if (n_target < self.sp_threshold or self.cfg.n_experts > 0):
            return None
        width = self._sp_padded_width(n_target)
        if width is None:
            return None
        jnp = self._jnp
        padded = np.zeros((1, width), np.int32)
        padded[0, :n_target] = prompt_ids[:n_target]
        cache = self._sp_prefill_jit(self.params, jnp.asarray(padded))
        with self._metrics_lock:
            self._sp_prefills += 1
        return dict(cache, index=jnp.asarray(n_target, jnp.int32))

    def _advance_prefill(self, pending) -> bool:
        request = pending.request
        reuse = (pending.plan.n_reuse_tokens
                 if pending.plan is not None else 0)
        if (pending.cache is None and reuse == 0 and
                not request.cancelled):
            t0 = time.perf_counter()
            cache = self._try_sp_prefill(request.prompt_ids,
                                         pending.n_target)
            if cache is not None:
                pending.cache = cache
                pending.consumed = pending.n_target
                request.span.mark_prefill_chunk(
                    time.perf_counter() - t0)
                self._record_chunk()
                self._coordinator.broadcast(
                    coordinator_lib.CMD_PREFILL,
                    slot=pending.slot_id, tokens=pending.n_target,
                    sp=self._sp_degree)
                return self._finish_prefill(pending)
        return super()._advance_prefill(pending)

    def _prefill_private(self, prompt_ids: List[int],
                         n_target: int) -> Dict[str, Any]:
        """Export-side prefill (`export_prefill`): long prompts go
        sequence-parallel here too — a prefill-role slice exports
        100k-token KV without the chunk ladder."""
        cache = self._try_sp_prefill(prompt_ids, n_target)
        if cache is not None:
            return cache
        return super()._prefill_private(prompt_ids, n_target)

    # ----------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        stats = super().stats()
        slice_stats = self._coordinator.stats()
        with self._metrics_lock:
            slice_stats['sp_prefills'] = self._sp_prefills
        slice_stats['sp_degree'] = self._sp_degree
        slice_stats['tensor_degree'] = int(
            self._slice_mesh.shape.get('tensor', 1))
        slice_stats['sp_threshold'] = self.sp_threshold
        stats['num_hosts'] = self.num_hosts
        stats['slice'] = slice_stats
        return stats

    def stop(self) -> None:
        super().stop()
        self._coordinator.close()


# ----------------------------------------------------------- real slices


class FollowerExecutor:
    """Execute the rank-0 command log against REAL local devices.

    A follower rank of a real slice holds the same weights and the
    same engine geometry as rank 0; every broadcast command carries
    rank 0's host-side scheduling decision (which slot, which pages,
    which drafts), so replaying the log with the SAME jitted functions
    reproduces rank 0's device state bit-for-bit — that is the whole
    gang contract: identical SPMD dispatches in identical order.

    Command semantics:

    - ``TICK``: one jitted engine step; a ``spec`` payload (the draft
      batch rank 0's n-gram drafters proposed) selects the speculative
      verify tick instead — same attention kernel either way.
    - ``ADMIT``: replay the chunked prefill of prompt positions
      ``[0, length)`` into a private cache, scatter it into the page
      row rank 0's planner allocated (or the dense slot), point the
      slot's block table at the row, and arm the sampler state
      (token/budget/stop set/key chain/sampling params).  Prefix
      reuse needs no special case: rewriting a reused page lands the
      identical KV bytes (causal KV at position i depends only on
      tokens [0..i], and both prefill paths are deterministic).
    - ``RELEASE``: park the slot's table on the null page, exactly
      when rank 0 does.
    - ``PREFILL``: informational (the SP one-shot); the ADMIT replay
      covers the KV, so nothing to do here.
    - ``SHUTDOWN``: handled by `follower_serve` (closes the loop).

    The executor keeps per-follower throughput honest: all heavy work
    goes through jits compiled once per shape bucket, mirroring the
    engine's compile-count discipline.
    """

    def __init__(self, cfg, params, *, max_len: int = 512,
                 slots: int = 4, prefill_chunk: int = 512,
                 kv_pages: Optional[int] = None, page_size: int = 16,
                 quantize_kv: bool = False, spec_tokens: int = 0,
                 max_top_k: int = 64, max_stop_ids: int = 16) -> None:
        import functools  # pylint: disable=import-outside-toplevel

        import jax  # pylint: disable=import-outside-toplevel
        import jax.numpy as jnp  # pylint: disable=import-outside-toplevel

        from skypilot_tpu.models import decode  # pylint: disable=import-outside-toplevel
        from skypilot_tpu.ops import paged_attention as paged_attention_lib  # pylint: disable=import-outside-toplevel
        from skypilot_tpu.serve import sampler as sampler_lib  # pylint: disable=import-outside-toplevel
        self.cfg = cfg
        self.params = params
        self.max_len = int(max_len)
        self.prefill_chunk = int(prefill_chunk)
        self._jnp = jnp
        self._sampler = sampler_lib.SlotSampler(int(max_top_k),
                                                int(max_stop_ids))
        self._paged = kv_pages is not None
        self._page_size = int(page_size)
        self._commands = 0
        if self._paged:
            kernel = paged_attention_lib.decode_kernel_choice()
            self._step = jax.jit(
                functools.partial(decode.paged_engine_step, cfg,
                                  max_top_k=int(max_top_k),
                                  kernel=kernel),
                donate_argnums=(2,))
            self._spec_step = jax.jit(
                functools.partial(decode.paged_spec_engine_step, cfg,
                                  max_top_k=int(max_top_k),
                                  kernel=kernel),
                donate_argnums=(2,))
            self._admit_paged = jax.jit(decode.paged_admit_slot,
                                        donate_argnums=(0,))
            self._release_paged = jax.jit(decode.paged_release_slot,
                                          donate_argnums=(0,))
            self._insert_pages = jax.jit(
                decode.insert_prefill_pages,
                static_argnames=('first_page',), donate_argnums=(0,))
            self._cache = decode.init_paged_cache(
                cfg, int(kv_pages), self._page_size, int(slots),
                self.max_len // self._page_size,
                quantize_kv=bool(quantize_kv))
        else:
            if spec_tokens:
                raise ValueError('spec_tokens requires the paged KV '
                                 'engine (kv_pages)')
            self._step = jax.jit(
                functools.partial(decode.engine_step, cfg,
                                  max_top_k=int(max_top_k)),
                donate_argnums=(2,))
            self._insert = jax.jit(decode.insert_prefill,
                                   donate_argnums=(0,))
            self._cache = decode.init_slot_cache(cfg, int(slots),
                                                 self.max_len)
        self._state = decode.init_engine_state(int(slots),
                                               int(max_stop_ids))
        self._prefill = jax.jit(
            lambda p, toks: decode.prefill(cfg, p, toks,
                                           max_len=self.max_len))
        self._prefill_chunk_jit = jax.jit(
            lambda p, toks, cache: decode.prefill_chunk(
                cfg, p, toks, cache),
            donate_argnums=(2,))

    def _bucket(self, n: int) -> int:
        for b in batching_engine_lib._PREFILL_BUCKETS:  # pylint: disable=protected-access
            if n <= b:
                return b
        return n

    def _replay_prefill(self, prompt: List[int], length: int):
        """Chunked prefill of prompt positions [0, length) — the same
        bucket ladder the engine runs, so follower compile counts stay
        bounded by the same buckets."""
        import numpy as np  # pylint: disable=import-outside-toplevel
        jnp = self._jnp
        chunk = self.prefill_chunk
        take = min(length, chunk)
        bucket = min(self._bucket(take), self.max_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :take] = prompt[:take]
        _, cache = self._prefill(self.params, jnp.asarray(padded))
        cache = dict(cache, index=jnp.asarray(take, jnp.int32))
        consumed = take
        while consumed < length:
            take = min(length - consumed, chunk)
            width = min(self._bucket(take), chunk,
                        self.max_len - consumed)
            piece = np.zeros((1, width), np.int32)
            piece[0, :take] = prompt[consumed:consumed + take]
            _, cache = self._prefill_chunk_jit(self.params,
                                               jnp.asarray(piece),
                                               cache)
            cache = dict(cache,
                         index=jnp.asarray(consumed + take, jnp.int32))
            consumed += take
        return cache

    def _pad_row(self, row: List[int]):
        import numpy as np  # pylint: disable=import-outside-toplevel
        padded = np.zeros((self.max_len // self._page_size,), np.int32)
        padded[:len(row)] = row
        return self._jnp.asarray(padded)

    def _admit(self, payload: Dict[str, Any]) -> None:
        import numpy as np  # pylint: disable=import-outside-toplevel
        jnp = self._jnp
        slot = int(payload['slot'])
        length = int(payload['length'])
        prompt = payload['prompt']
        row = payload.get('row')
        if length > 0:
            pre = self._replay_prefill(prompt, length)
            if self._paged:
                n_pages = -(-length // self._page_size)
                self._cache = self._insert_pages(
                    self._cache, pre,
                    np.asarray(row[:n_pages], np.int32), first_page=0)
            else:
                self._cache = self._insert(self._cache, slot, pre,
                                           length)
        if self._paged:
            self._cache = self._admit_paged(
                self._cache, slot, self._pad_row(row), length)
        elif length == 0:
            self._cache = dict(
                self._cache,
                lengths=self._cache['lengths'].at[slot].set(0))
        self._state = self._sampler.admit(
            self._state, slot, int(payload['token']),
            int(payload['remaining']),
            frozenset(payload['stop_ids']),
            jnp.asarray(payload['key'], jnp.uint32),
            float(payload['temperature']), int(payload['top_k']))

    def __call__(self, cmd) -> None:
        payload = cmd.payload
        self._commands += 1
        if cmd.kind == coordinator_lib.CMD_TICK:
            drafts = payload.get('spec') if payload else None
            if drafts is not None:
                out = self._spec_step(
                    self.params, self._state, self._cache,
                    self._jnp.asarray(drafts, self._jnp.int32))
                self._state, self._cache = out[0], out[1]
            else:
                out = self._step(self.params, self._state, self._cache)
                self._state, self._cache = out[0], out[1]
        elif cmd.kind == coordinator_lib.CMD_ADMIT:
            # Pre-follower-executor ADMITs carried only slot/tokens;
            # tolerate them so mixed-version logs replay (state just
            # won't mirror — the emulated tier).
            if payload and 'prompt' in payload:
                self._admit(payload)
        elif cmd.kind == coordinator_lib.CMD_RELEASE:
            if self._paged:
                self._cache = self._release_paged(self._cache,
                                                  int(payload['slot']))
        # CMD_PREFILL: SP one-shot notification — the ADMIT replay
        # writes the same KV, nothing to mirror here.


def follower_main(rank: int, coordinator_address: str,
                  executor: Optional[FollowerExecutor] = None) -> None:
    """Rank > 0 of a REAL slice: connect to rank 0's rank-protocol
    port and execute the command log.  With an executor (built from
    the same model/geometry flags as rank 0), every command dispatches
    the matching jitted step on this host's local devices; without
    one, the process just holds the gang together (the emulated tier,
    where all virtual devices live on rank 0)."""
    sock = coordinator_lib.follower_connect(coordinator_address, rank)
    logger.info(f'slice follower rank {rank} connected to '
                f'{coordinator_address}')
    coordinator_lib.follower_serve(sock, rank, executor)


def _bench_prefill(args) -> None:
    """--bench-prefill: time ONE sequence-parallel prefill at a given
    host count (used by bench_serve.py's long-context scaling probe;
    each invocation is its own process so CPU affinity can model
    per-host compute)."""
    import flax.linen as nn  # pylint: disable=import-outside-toplevel
    import jax  # pylint: disable=import-outside-toplevel
    import jax.numpy as jnp  # pylint: disable=import-outside-toplevel
    import numpy as np  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.models import configs  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.models import decode  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.models.transformer import Transformer  # pylint: disable=import-outside-toplevel

    cfg = configs.get_config(args.model)
    params = nn.meta.unbox(Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))['params'])
    n = int(args.prompt_len)
    sp = int(args.sequence or args.num_hosts)
    width = -(-n // sp) * sp
    max_len = width + 16
    mesh = build_slice_mesh(args.num_hosts, cfg, sequence=sp)
    rng = np.random.default_rng(0)
    tokens = np.zeros((1, width), np.int32)
    tokens[0, :n] = rng.integers(1, cfg.vocab_size - 1, size=n)
    tokens = jnp.asarray(tokens)
    fn = jax.jit(lambda p, t: decode.prefill_sp(cfg, p, t, mesh=mesh,
                                                max_len=max_len))
    cache = fn(params, tokens)             # compile
    jax.block_until_ready(cache)
    times = []
    for _ in range(int(args.iters)):
        t0 = time.perf_counter()
        cache = fn(params, tokens)
        jax.block_until_ready(cache)
        times.append(time.perf_counter() - t0)
    print(json.dumps({
        'num_hosts': int(args.num_hosts),
        'sequence': sp,
        'tensor': int(mesh.shape.get('tensor', 1)),
        'prompt_len': n,
        'prefill_s': sorted(times)[len(times) // 2],
        'prefill_s_all': [round(t, 6) for t in times],
    }))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--num-hosts', type=int,
                        default=int(os.environ.get(
                            'SKYTPU_NUM_HOSTS', '1')))
    parser.add_argument('--rank', type=int,
                        default=int(os.environ.get(
                            'SKYTPU_HOST_RANK', '0')))
    parser.add_argument('--coordinator',
                        default=os.environ.get(
                            'SKYTPU_COORDINATOR_ADDRESS'))
    parser.add_argument('--model', default='tiny')
    parser.add_argument('--max-len', type=int, default=512)
    parser.add_argument('--max-batch', type=int, default=8)
    parser.add_argument('--prefill-chunk', type=int, default=512)
    parser.add_argument('--bench-prefill', action='store_true')
    parser.add_argument('--prompt-len', type=int, default=2048)
    parser.add_argument('--sequence', type=int, default=None)
    parser.add_argument('--iters', type=int, default=3)
    args, extra = parser.parse_known_args()
    if args.bench_prefill:
        _bench_prefill(args)
        return
    if args.rank > 0:
        # Follower rank of a real slice: the rank-protocol port is the
        # JAX coordinator's + a fixed offset.  The executor mirrors
        # rank 0's engine geometry: model/max-len/max-batch/prefill-
        # chunk from the (gang-identical) CLI, KV pool shape from the
        # SKYTPU_SERVE_* env the task YAML exports to every worker.
        if not args.coordinator:
            raise SystemExit('rank > 0 needs --coordinator (or the '
                             'gang env contract)')
        import flax.linen as nn  # pylint: disable=import-outside-toplevel
        import jax  # pylint: disable=import-outside-toplevel
        import jax.numpy as jnp  # pylint: disable=import-outside-toplevel

        from skypilot_tpu.models import configs  # pylint: disable=import-outside-toplevel
        from skypilot_tpu.models.transformer import Transformer  # pylint: disable=import-outside-toplevel
        cfg = configs.get_config(args.model)
        params = nn.meta.unbox(Transformer(cfg).init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 8), jnp.int32))['params'])
        kv_pages_env = os.environ.get('SKYTPU_SERVE_KV_PAGES')
        executor = FollowerExecutor(
            cfg, params, max_len=args.max_len, slots=args.max_batch,
            prefill_chunk=args.prefill_chunk,
            kv_pages=(int(kv_pages_env) if kv_pages_env else None),
            page_size=int(os.environ.get('SKYTPU_SERVE_PAGE_SIZE',
                                         '16')),
            quantize_kv=os.environ.get('SKYTPU_SERVE_KV_INT8',
                                       '') == '1',
            spec_tokens=int(os.environ.get('SKYTPU_SERVE_SPEC_TOKENS',
                                           '0')))
        host, _, port = args.coordinator.rpartition(':')
        follower_main(args.rank,
                      f'{host}:{int(port) + SLICE_COORD_PORT_OFFSET}',
                      executor)
        return
    # Rank 0: hand over to the model server CLI with num_hosts set —
    # one entrypoint for `run: python -m skypilot_tpu.serve.
    # slice_replica` task YAMLs.
    import sys  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.serve import model_server  # pylint: disable=import-outside-toplevel
    sys.argv = ([sys.argv[0], '--num-hosts', str(args.num_hosts),
                 '--model', args.model,
                 '--max-len', str(args.max_len),
                 '--max-batch', str(args.max_batch),
                 '--prefill-chunk', str(args.prefill_chunk),
                 '--continuous-batching'] +
                list(extra))
    model_server.main()


if __name__ == '__main__':
    main()
