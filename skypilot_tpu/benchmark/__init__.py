"""Benchmark harness: try one task on N candidate resources, report
$/step and time-to-K-steps.

Parity: /root/reference/sky/benchmark/ (benchmark_utils.py:432-629
launch-in-parallel + log collection, benchmark_state.py sqlite) — the
north-star tool for TPU-vs-GPU fungibility decisions (BASELINE.md).
"""
from skypilot_tpu.benchmark.benchmark_utils import down_benchmark_clusters
from skypilot_tpu.benchmark.benchmark_utils import get_benchmark_results
from skypilot_tpu.benchmark.benchmark_utils import launch_benchmark

__all__ = ['down_benchmark_clusters', 'get_benchmark_results',
           'launch_benchmark']
