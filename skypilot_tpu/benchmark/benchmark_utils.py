"""Benchmark orchestration: parallel candidate launches + result pull.

Parity: /root/reference/sky/benchmark/benchmark_utils.py:432-629 —
launch the same task once per candidate Resources (each on its own
cluster), let the in-loop callback write `summary.json`, pull it back
over the cluster's command runners, and score $/step.
"""
from __future__ import annotations

import copy
import json
import os
import tempfile
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.benchmark import benchmark_state
from skypilot_tpu.callbacks import base as callback_base
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

_REMOTE_LOG_DIR = '~/.skytpu/benchmark_logs'


def _cluster_name(benchmark: str, index: int) -> str:
    return f'skytpu-bench-{benchmark}-{index}'


def launch_benchmark(task: task_lib.Task, benchmark: str,
                     candidates: List[Any],
                     idle_minutes_to_autostop: Optional[int] = 5
                     ) -> List[str]:
    """Launch `task` once per candidate Resources; returns clusters.

    Each candidate cluster gets SKYTPU_BENCHMARK_LOG_DIR exported so
    skytpu_callback lands summaries where `get_benchmark_results` looks.
    """
    from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
    benchmark_state.add_benchmark(
        benchmark, common_utils.dump_yaml_str(task.to_yaml_config()))

    clusters = []

    def _launch_one(item):
        index, resources = item
        candidate_task = copy.deepcopy(task)
        candidate_task.set_resources(resources)
        candidate_task.update_envs(
            {callback_base.ENV_LOG_DIR: _REMOTE_LOG_DIR})
        name = _cluster_name(benchmark, index)
        execution.launch(
            candidate_task, cluster_name=name, stream_logs=False,
            detach_run=True,
            idle_minutes_to_autostop=idle_minutes_to_autostop)
        return name

    results = subprocess_utils.run_in_parallel(
        _launch_one, list(enumerate(candidates)))
    clusters.extend(results)
    benchmark_state.set_benchmark_clusters(benchmark, clusters)
    return clusters


def get_benchmark_results(benchmark: str) -> List[Dict[str, Any]]:
    """Pull summary.json from each candidate cluster and score it."""
    from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
    record = benchmark_state.get_benchmark(benchmark)
    if record is None:
        raise exceptions.SkyTpuError(f'No benchmark named {benchmark!r}.')
    for name in benchmark_state.get_benchmark_clusters(benchmark):
        try:
            handle = backend_utils.check_cluster_available(name)
        except exceptions.SkyTpuError as e:
            logger.warning(f'benchmark cluster {name} unavailable: {e}')
            continue
        summary = _pull_summary(handle)
        if summary is not None:
            resources = handle.launched_resources
            cost_per_hour = (resources.get_cost(3600.0)
                             if resources is not None else 0.0)
            benchmark_state.add_result(
                benchmark, name, str(resources), cost_per_hour, summary)
    return benchmark_state.get_results(benchmark)


def _pull_summary(handle) -> Optional[Dict[str, Any]]:
    head = handle.get_command_runners()[0]
    with tempfile.TemporaryDirectory() as tmp:
        local = os.path.join(tmp, 'summary.json')
        try:
            head.rsync(f'{_REMOTE_LOG_DIR}/{callback_base.SUMMARY_FILE}',
                       local, up=False, stream_logs=False)
            with open(local, encoding='utf-8') as f:
                return json.load(f)
        except (exceptions.SkyTpuError, OSError, ValueError) as e:
            logger.warning(f'no benchmark summary from '
                           f'{handle.cluster_name}: {e}')
            return None


def down_benchmark_clusters(benchmark: str) -> None:
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    for name in benchmark_state.get_benchmark_clusters(benchmark):
        try:
            core.down(name)
        except (exceptions.SkyTpuError, ValueError) as e:
            logger.warning(f'failed to tear down {name}: {e}')
