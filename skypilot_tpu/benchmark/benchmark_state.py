"""Benchmark state store (sqlite).

Parity: /root/reference/sky/benchmark/benchmark_state.py.
"""
from __future__ import annotations

import json
import os
import pathlib
import sqlite3
import time
from typing import Any, Dict, List, Optional

_CREATE_BENCHMARKS = """\
CREATE TABLE IF NOT EXISTS benchmarks (
    name TEXT PRIMARY KEY,
    task_yaml TEXT,
    clusters TEXT DEFAULT '[]',
    launched_at REAL
)"""

_CREATE_RESULTS = """\
CREATE TABLE IF NOT EXISTS benchmark_results (
    benchmark TEXT,
    cluster TEXT,
    resources TEXT,
    cost_per_hour REAL,
    num_steps INTEGER,
    seconds_per_step REAL,
    first_step_seconds REAL,
    cost_per_step REAL,
    raw_summary TEXT,
    PRIMARY KEY (benchmark, cluster)
)"""


def _db_path() -> str:
    path = os.environ.get('SKYTPU_BENCHMARK_DB')
    if path is None:
        from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
        path = os.path.join(common_utils.skytpu_home(), 'benchmark.db')
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    return path


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(), timeout=10)
    conn.execute(_CREATE_BENCHMARKS)
    conn.execute(_CREATE_RESULTS)
    return conn


def add_benchmark(name: str, task_yaml: str) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO benchmarks VALUES (?,?,?,?)',
            (name, task_yaml, '[]', time.time()))


def set_benchmark_clusters(name: str, clusters: List[str]) -> None:
    with _conn() as conn:
        conn.execute('UPDATE benchmarks SET clusters=? WHERE name=?',
                     (json.dumps(clusters), name))


def get_benchmark_clusters(name: str) -> List[str]:
    with _conn() as conn:
        row = conn.execute(
            'SELECT clusters FROM benchmarks WHERE name=?',
            (name,)).fetchone()
    return json.loads(row[0]) if row and row[0] else []


def add_result(benchmark: str, cluster: str, resources: str,
               cost_per_hour: float, summary: Dict[str, Any]) -> None:
    sps = summary.get('seconds_per_step')
    cost_per_step = (cost_per_hour / 3600.0 * sps
                     if sps is not None else None)
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO benchmark_results VALUES '
            '(?,?,?,?,?,?,?,?,?)',
            (benchmark, cluster, resources, cost_per_hour,
             summary.get('num_steps'), sps,
             summary.get('first_step_seconds'), cost_per_step,
             json.dumps(summary)))


def get_benchmarks() -> List[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        return [dict(r) for r in conn.execute(
            'SELECT * FROM benchmarks ORDER BY launched_at').fetchall()]


def get_results(benchmark: str) -> List[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        return [dict(r) for r in conn.execute(
            'SELECT * FROM benchmark_results WHERE benchmark=? '
            'ORDER BY cost_per_step', (benchmark,)).fetchall()]


def remove_benchmark(name: str) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM benchmarks WHERE name=?', (name,))
        conn.execute('DELETE FROM benchmark_results WHERE benchmark=?',
                     (name,))


def get_benchmark(name: str) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        row = conn.execute('SELECT * FROM benchmarks WHERE name=?',
                           (name,)).fetchone()
    return dict(row) if row else None
