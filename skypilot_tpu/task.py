"""Task: the user-facing unit of work.

Parity: /root/reference/sky/task.py:73-1194 (name/setup/run/workdir/
num_nodes/envs/file_mounts/storage_mounts/resources/service, YAML round-trip,
env-var substitution, `>>` DAG chaining). TPU-first addition: tasks carry an
optional `checkpoint_dir` making the checkpoint/auto-resume contract
first-class (SURVEY.md §5 — the reference leaves this to user convention).
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Set, Union

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.utils import common_utils

_TASK_NAME_RE = re.compile(r'^[a-zA-Z0-9]([-_.a-zA-Z0-9]*[a-zA-Z0-9])?$')

CommandOrGenerator = Union[None, str, Callable[[int, List[str]], Optional[str]]]


def _substitute_env_vars(text: str, envs: Dict[str, str]) -> str:
    """Expand $VAR / ${VAR} for declared env vars only (parity task.py:73)."""

    def repl(m: 're.Match[str]') -> str:
        name = m.group(1) or m.group(2)
        return envs.get(name, m.group(0))

    return re.sub(r'\$\{(\w+)\}|\$(\w+)', repl, text)


class Task:
    """A task: setup + run commands executed on provisioned resources."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: CommandOrGenerator = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        envs: Optional[Dict[str, str]] = None,
        file_mounts: Optional[Dict[str, str]] = None,
        storage_mounts: Optional[Dict[str, Any]] = None,
        checkpoint_dir: Optional[str] = None,
    ) -> None:
        self.name = name
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self.num_nodes = num_nodes if num_nodes is not None else 1
        self._envs = dict(envs) if envs else {}
        # file_mounts: {remote_path: local_path_or_cloud_uri}
        self.file_mounts: Dict[str, str] = dict(file_mounts) if file_mounts else {}
        # storage_mounts: {remote_path: data.Storage} — filled by set_storage_mounts
        self.storage_mounts: Dict[str, Any] = dict(storage_mounts) if storage_mounts else {}
        self.checkpoint_dir = checkpoint_dir
        self._resources: Set[resources_lib.Resources] = {
            resources_lib.Resources()
        }
        self.service: Optional[Any] = None  # serve.SkyServiceSpec
        self.best_resources: Optional[resources_lib.Resources] = None
        # Estimator hooks for the optimizer's TIME target
        # (parity task.py:687 set_time_estimator).
        self._time_estimator: Optional[Callable[[resources_lib.Resources],
                                                float]] = None
        self.estimated_outputs_size_gigabytes: Optional[float] = None
        self._validate()

    # ---------------------------------------------------------- validation

    def _validate(self) -> None:
        if self.name is not None and not _TASK_NAME_RE.match(self.name):
            raise exceptions.InvalidTaskError(
                f'Invalid task name {self.name!r}.')
        if self.num_nodes < 1:
            raise exceptions.InvalidTaskError(
                f'num_nodes must be >= 1, got {self.num_nodes}.')
        if self.run is not None and not (isinstance(self.run, str) or
                                         callable(self.run)):
            raise exceptions.InvalidTaskError(
                'run must be a string command or a callable '
                '(node_rank, host_ips) -> command.')
        if self.workdir is not None:
            expanded = os.path.expanduser(self.workdir)
            if not os.path.isdir(expanded):
                raise exceptions.InvalidTaskError(
                    f'workdir {self.workdir!r} is not a directory.')
        for dst, src in self.file_mounts.items():
            if not os.path.isabs(dst) and not dst.startswith('~'):
                raise exceptions.InvalidTaskError(
                    f'file_mounts destination must be absolute or ~-based, '
                    f'got {dst!r}.')
            from skypilot_tpu.data import storage as storage_lib  # pylint: disable=import-outside-toplevel
            if src.startswith(storage_lib.BUCKET_URL_PREFIXES):
                continue
            if not os.path.exists(os.path.expanduser(src)):
                raise exceptions.InvalidTaskError(
                    f'file_mounts source {src!r} does not exist.')
        for dst in self.storage_mounts:
            if not os.path.isabs(dst) and not dst.startswith('~'):
                raise exceptions.InvalidTaskError(
                    f'storage mount destination must be absolute or '
                    f'~-based, got {dst!r}.')

    # ---------------------------------------------------------- resources

    def set_resources(
        self, resources: Union[resources_lib.Resources,
                               Set[resources_lib.Resources],
                               List[resources_lib.Resources]]
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = {resources}
        self._resources = set(resources)
        return self

    @property
    def resources(self) -> Set[resources_lib.Resources]:
        return self._resources

    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    def update_envs(self, envs: Dict[str, str]) -> 'Task':
        self._envs.update(envs)
        return self

    def set_time_estimator(
            self, estimator: Callable[[resources_lib.Resources],
                                      float]) -> 'Task':
        """Seconds-to-complete estimate per candidate resource (optimizer
        TIME target; parity reference task.py:687)."""
        self._time_estimator = estimator
        return self

    def estimate_runtime(self, resources: resources_lib.Resources) -> float:
        if self._time_estimator is None:
            raise exceptions.InvalidTaskError(
                f'Task {self.name!r} has no time estimator; '
                'optimize with minimize=COST or call set_time_estimator().')
        return self._time_estimator(resources)

    def set_storage_mounts(self, storage_mounts: Dict[str, Any]) -> 'Task':
        self.storage_mounts = dict(storage_mounts)
        return self

    # --------------------------------------------------------------- yaml

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Task':
        config = dict(config)
        envs = {
            str(k): str(v) for k, v in (config.pop('envs', None) or {}).items()
        }

        def sub(v: Optional[str]) -> Optional[str]:
            return _substitute_env_vars(v, envs) if isinstance(v, str) else v

        known = {
            'name', 'setup', 'run', 'workdir', 'num_nodes', 'envs',
            'file_mounts', 'resources', 'service', 'checkpoint_dir',
            'experimental',
        }
        unknown = set(config) - known
        if unknown:
            raise exceptions.InvalidTaskError(
                f'Unknown task fields: {sorted(unknown)}')
        # file_mounts values may be plain paths/URLs (copied via rsync)
        # or storage configs (dicts) that become bucket-backed
        # storage_mounts (parity: reference task.py file_mounts dual
        # syntax).
        file_mounts = {}
        storage_mounts = {}
        for dst, src in (config.get('file_mounts') or {}).items():
            if isinstance(src, dict):
                from skypilot_tpu.data import storage as storage_lib  # pylint: disable=import-outside-toplevel
                storage_mounts[dst] = storage_lib.Storage.from_yaml_config(
                    {k: sub(v) if isinstance(v, str) else v
                     for k, v in src.items()})
            else:
                file_mounts[dst] = sub(src)
        task = cls(
            name=config.get('name'),
            setup=sub(config.get('setup')),
            run=sub(config.get('run')),
            workdir=sub(config.get('workdir')),
            num_nodes=config.get('num_nodes'),
            envs=envs,
            file_mounts=file_mounts,
            storage_mounts=storage_mounts,
            checkpoint_dir=sub(config.get('checkpoint_dir')),
        )
        resources_config = config.get('resources')
        if resources_config is not None:
            if isinstance(resources_config, list):
                task.set_resources({
                    resources_lib.Resources.from_yaml_config(r)
                    for r in resources_config
                })
            else:
                task.set_resources(
                    resources_lib.Resources.from_yaml_config(resources_config))
        service = config.get('service')
        if service is not None:
            from skypilot_tpu.serve import service_spec  # pylint: disable=import-outside-toplevel
            task.service = service_spec.SkyServiceSpec.from_yaml_config(service)
        return task

    @classmethod
    def from_yaml(cls, yaml_path: str) -> 'Task':
        config = common_utils.read_yaml(os.path.expanduser(yaml_path))
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f'{yaml_path} is not a YAML mapping.')
        return cls.from_yaml_config(config)

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {}
        for key, value in (
            ('name', self.name),
            ('workdir', self.workdir),
            ('setup', self.setup),
            ('run', self.run if isinstance(self.run, str) else None),
            ('checkpoint_dir', self.checkpoint_dir),
        ):
            if value is not None:
                config[key] = value
        if self.num_nodes != 1:
            config['num_nodes'] = self.num_nodes
        if self._envs:
            config['envs'] = dict(self._envs)
        if self.file_mounts or self.storage_mounts:
            config['file_mounts'] = dict(self.file_mounts)
            for dst, storage in self.storage_mounts.items():
                config['file_mounts'][dst] = storage.to_yaml_config()
        if len(self._resources) == 1:
            r = next(iter(self._resources)).to_yaml_config()
            if r:
                config['resources'] = r
        elif self._resources:
            config['resources'] = [r.to_yaml_config() for r in self._resources]
        if self.service is not None:
            config['service'] = self.service.to_yaml_config()
        return config

    # ----------------------------------------------------------------- dag

    def __rshift__(self, other: 'Task') -> 'Task':
        """task_a >> task_b adds an edge in the ambient Dag context."""
        from skypilot_tpu import dag as dag_lib  # pylint: disable=import-outside-toplevel
        dag = dag_lib.get_current_dag()
        if dag is None:
            raise exceptions.InvalidTaskError(
                'task >> task requires an active `with sky.Dag():` context.')
        dag.add_edge(self, other)
        return other

    def __repr__(self) -> str:
        label = self.name or '<unnamed>'
        num_resources = len(self._resources)
        res = (repr(next(iter(self._resources)))
               if num_resources == 1 else f'{num_resources} candidates')
        return f'<Task {label} nodes={self.num_nodes} {res}>'
