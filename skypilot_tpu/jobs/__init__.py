"""Managed jobs: launch-and-forget with preemption auto-recovery.

Parity: /root/reference/sky/jobs/ (core.py, controller.py,
recovery_strategy.py, state.py) — a controller process supervises each
job, relaunching its cluster on preemption/hardware loss and resuming
from the framework checkpoint contract (which the reference leaves to
user convention; SURVEY.md §5).

TPU-first specifics: spot-TPU slices must be *deleted* before relaunch
(a preempted TPU-VM lingers in a broken state — reference gcp.py:928-934
behavior generalized), multi-host slices fail as a unit, and recovered
tasks find their checkpoint dir pre-mounted (SKYTPU_CHECKPOINT_DIR).
"""
from skypilot_tpu.jobs.core import cancel
from skypilot_tpu.jobs.core import launch
from skypilot_tpu.jobs.core import queue
from skypilot_tpu.jobs.core import tail_logs
from skypilot_tpu.jobs.state import ManagedJobStatus

__all__ = ['ManagedJobStatus', 'cancel', 'launch', 'queue', 'tail_logs']
