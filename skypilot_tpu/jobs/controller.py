"""Per-job controller: supervise, detect preemption, recover.

Parity: /root/reference/sky/jobs/controller.py:46-341 (JobsController —
one process per managed job, running the DAG's tasks in order; a monitor
loop classifies user failure vs preemption and triggers the recovery
strategy).  Runnable directly:

    python -m skypilot_tpu.jobs.controller --job-id N --dag-yaml PATH

TPU specifics inherited from the strategy layer: preempted slices are
terminated before relaunch; recovered tasks resume from the checkpoint
contract (SKYTPU_CHECKPOINT_DIR / storage mounts travel with the task).
"""
from __future__ import annotations

import argparse
import os
import time
import traceback
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import status_lib
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.jobs import constants
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import state
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.skylet import job_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import dag_utils

logger = sky_logging.init_logger(__name__)


def _check_gap() -> float:
    return float(
        os.environ.get('SKYTPU_JOB_STATUS_CHECK_GAP',
                       constants.JOB_STATUS_CHECK_GAP_SECONDS))


def _started_gap() -> float:
    return float(
        os.environ.get('SKYTPU_JOB_STARTED_CHECK_GAP',
                       constants.JOB_STARTED_CHECK_GAP_SECONDS))


class JobsController:

    def __init__(self, job_id: int, dag_yaml: str) -> None:
        self.job_id = job_id
        self.dag = dag_utils.load_chain_dag_from_yaml(dag_yaml)

    # ------------------------------------------------------------ public

    def run(self) -> None:
        state.set_controller_pid(self.job_id, os.getpid())
        try:
            for task_id, task in enumerate(self.dag.tasks):
                succeeded = self._run_one_task(task_id, task)
                if not succeeded:
                    # Remaining tasks in the chain never start.
                    for later_id in range(task_id + 1, len(self.dag.tasks)):
                        state.set_status(self.job_id, later_id,
                                         state.ManagedJobStatus.CANCELLED)
                    return
        except Exception as e:  # pylint: disable=broad-except
            # ANY controller crash must land the job in a terminal state,
            # or clients block forever on non-terminal rows.
            logger.error(traceback.format_exc())
            for task_id in range(len(self.dag.tasks)):
                cur = self._task_status(task_id)
                if cur is not None and not cur.is_terminal():
                    state.set_status(
                        self.job_id, task_id,
                        state.ManagedJobStatus.FAILED_CONTROLLER,
                        failure_reason=common_utils.format_exception(e))

    def _task_status(self, task_id: int) -> Optional[state.ManagedJobStatus]:
        for rec in state.get_job_records(self.job_id):
            if rec['task_id'] == task_id:
                return state.ManagedJobStatus(rec['status'])
        return None

    # ----------------------------------------------------------- workers

    def _cluster_name(self, task_id: int, task) -> str:
        base = task.name or 'task'
        return f'{base}-{self.job_id}-{task_id}'

    def _cancel_requested(self) -> bool:
        status = state.get_status(self.job_id)
        return status is state.ManagedJobStatus.CANCELLING

    def _run_one_task(self, task_id: int, task) -> bool:
        """Returns True iff the task SUCCEEDED."""
        job_id = self.job_id
        cluster_name = self._cluster_name(task_id, task)
        journal = events_lib.job_journal(job_id)
        state.set_cluster_name(job_id, task_id, cluster_name)
        state.set_status(job_id, task_id, state.ManagedJobStatus.STARTING)
        journal.append('task_start', job_id=job_id, task_id=task_id,
                       task=task.name, cluster=cluster_name)
        # The task lifecycle must terminate on EVERY exit — early
        # failure, cancellation, a controller exception mid-supervision
        # — so the end event is emitted from one finally; the
        # supervision loop records the terminal status into `end`
        # ('error' survives only when an exception escapes it).
        end = {'status': 'error', 'recoveries': 0}
        try:
            return self._supervise_task(task_id, task, cluster_name,
                                        journal, end)
        finally:
            journal.append('task_end', job_id=job_id, task_id=task_id,
                           **end)

    def _supervise_task(self, task_id: int, task, cluster_name: str,
                        journal, end: dict) -> bool:
        """Launch + babysit one task; writes the terminal status and
        recovery count into `end` (journaled as task_end by the
        caller's finally)."""
        job_id = self.job_id
        strategy = recovery_strategy.StrategyExecutor.make(
            cluster_name, task, job_id=job_id, task_id=task_id)
        try:
            remote_job_id = strategy.launch()
        except exceptions.ResourcesUnavailableError as e:
            state.set_status(
                job_id, task_id, state.ManagedJobStatus.FAILED_NO_RESOURCE,
                failure_reason=common_utils.format_exception(e))
            end.update(
                status=state.ManagedJobStatus.FAILED_NO_RESOURCE.value,
                recoveries=strategy.recovery_attempts)
            return False
        state.set_status(job_id, task_id, state.ManagedJobStatus.RUNNING)

        time.sleep(_started_gap())
        while True:
            if self._cancel_requested():
                strategy.cleanup_cluster()
                state.set_status(job_id, task_id,
                                 state.ManagedJobStatus.CANCELLED)
                end.update(status=state.ManagedJobStatus.CANCELLED.value,
                           recoveries=strategy.recovery_attempts)
                return False

            job_status = self._query_job_status(cluster_name,
                                                remote_job_id)
            if job_status is job_lib.JobStatus.SUCCEEDED:
                state.set_status(job_id, task_id,
                                 state.ManagedJobStatus.SUCCEEDED)
                end.update(status='SUCCEEDED',
                           recoveries=strategy.recovery_attempts)
                strategy.cleanup_cluster()
                return True
            if job_status in (job_lib.JobStatus.FAILED,
                              job_lib.JobStatus.FAILED_SETUP):
                # Classify before blaming user code: a gang whose rank
                # died because its HOST was reclaimed exits FAILED all
                # the same (fail-fast abort), but the cluster view
                # shows the partial loss — that is a preemption, and
                # charging it to the restart budget would burn the
                # budget on the cloud's behavior.
                cluster_status = self._query_cluster_status(cluster_name)
                if cluster_status is not status_lib.ClusterStatus.UP:
                    status_str = (cluster_status.value
                                  if cluster_status is not None
                                  else 'gone')
                    reason = (f'cluster {cluster_name} partially '
                              f'preempted/lost (status: {status_str}; '
                              f'gang failed)')
                    logger.info(f'job FAILED but cluster is '
                                f'{status_str}; classifying as '
                                f'preemption and recovering')
                    events_lib.jobs_preemptions().inc()
                    journal.append('preemption_detected', job_id=job_id,
                                   task_id=task_id, cluster=cluster_name,
                                   cluster_status=status_str,
                                   via='gang_failure')
                    state.set_recovering(job_id, task_id, reason=reason)
                    try:
                        remote_job_id = strategy.recover()
                    except exceptions.ResourcesUnavailableError as e:
                        state.set_status(
                            job_id, task_id,
                            state.ManagedJobStatus.FAILED_NO_RESOURCE,
                            failure_reason=common_utils.format_exception(
                                e))
                        end.update(
                            status=state.ManagedJobStatus
                            .FAILED_NO_RESOURCE.value,
                            recoveries=strategy.recovery_attempts)
                        return False
                    state.set_status(job_id, task_id,
                                     state.ManagedJobStatus.RUNNING)
                    time.sleep(_check_gap())
                    continue
                # User-code failure: bounded restarts, then fail the job
                # (parity: reference controller.py max_restarts_on_errors).
                if (strategy.restart_count_on_errors <
                        strategy.max_restarts_on_errors):
                    strategy.restart_count_on_errors += 1
                    logger.info(
                        f'user failure; restart '
                        f'{strategy.restart_count_on_errors}/'
                        f'{strategy.max_restarts_on_errors}')
                    state.set_recovering(
                        job_id, task_id,
                        reason=f'user code failed; restart '
                               f'{strategy.restart_count_on_errors}/'
                               f'{strategy.max_restarts_on_errors}')
                    remote_job_id = strategy.recover()
                    state.set_status(job_id, task_id,
                                     state.ManagedJobStatus.RUNNING)
                    continue
                failed_status = (
                    state.ManagedJobStatus.FAILED_SETUP
                    if job_status is job_lib.JobStatus.FAILED_SETUP else
                    state.ManagedJobStatus.FAILED)
                failure_reason = 'user code exited non-zero'
                recovery_reason = None
                if strategy.max_restarts_on_errors > 0:
                    # Restart budget exhausted: persist WHY the job is
                    # terminal (not just that it failed) and journal it
                    # — exhaustion used to be log-only.
                    recovery_reason = (
                        f'max_restarts_on_errors exhausted '
                        f'({strategy.restart_count_on_errors}/'
                        f'{strategy.max_restarts_on_errors}); last '
                        f'failure: {failure_reason}')
                    failure_reason = recovery_reason
                    journal.append(
                        'recovery_exhausted', job_id=job_id,
                        task_id=task_id,
                        restarts=strategy.restart_count_on_errors,
                        max_restarts=strategy.max_restarts_on_errors,
                        reason=failure_reason)
                state.set_status(
                    job_id, task_id, failed_status,
                    failure_reason=failure_reason,
                    last_recovery_reason=recovery_reason)
                end.update(status=failed_status.value,
                           recoveries=strategy.recovery_attempts)
                strategy.cleanup_cluster()
                return False
            if job_status is job_lib.JobStatus.CANCELLED:
                state.set_status(job_id, task_id,
                                 state.ManagedJobStatus.CANCELLED)
                end.update(status=state.ManagedJobStatus.CANCELLED.value,
                           recoveries=strategy.recovery_attempts)
                return False
            if job_status is None:
                # Cannot read the job queue: cluster preempted, hardware
                # lost, or still in a transient state — reconcile with
                # the cloud and recover (parity: reference
                # controller.py:195-340 anomaly path).
                cluster_status = self._query_cluster_status(cluster_name)
                if cluster_status is not status_lib.ClusterStatus.UP:
                    status_str = (cluster_status.value
                                  if cluster_status is not None
                                  else 'gone')
                    reason = (f'cluster {cluster_name} preempted/lost '
                              f'(status: {status_str})')
                    logger.info(
                        f'cluster {cluster_name} is '
                        f'{cluster_status}; recovering')
                    events_lib.jobs_preemptions().inc()
                    journal.append('preemption_detected', job_id=job_id,
                                   task_id=task_id, cluster=cluster_name,
                                   cluster_status=status_str)
                    state.set_recovering(job_id, task_id, reason=reason)
                    try:
                        remote_job_id = strategy.recover()
                    except exceptions.ResourcesUnavailableError as e:
                        state.set_status(
                            job_id, task_id,
                            state.ManagedJobStatus.FAILED_NO_RESOURCE,
                            failure_reason=common_utils.format_exception(
                                e))
                        end.update(
                            status=state.ManagedJobStatus
                            .FAILED_NO_RESOURCE.value,
                            recoveries=strategy.recovery_attempts)
                        return False
                    state.set_status(job_id, task_id,
                                     state.ManagedJobStatus.RUNNING)
            time.sleep(_check_gap())

    # ------------------------------------------------------------ helpers

    def _query_job_status(self, cluster_name: str,
                          remote_job_id: Optional[int]):
        from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
        try:
            # Chaos site: the 'preempt' effect downs the task cluster
            # behind the controller's back and raises — this poll then
            # reports None and the real preemption-detection path runs.
            chaos_injector.inject('jobs.status_poll', job_id=self.job_id,
                                  cluster=cluster_name)
            statuses = core.job_status(cluster_name, [remote_job_id]
                                       if remote_job_id else None)
            if not statuses:
                return None
            value = next(iter(statuses.values()))
            return job_lib.JobStatus(value) if value else None
        except exceptions.SkyTpuError:
            return None

    def _query_cluster_status(self, cluster_name: str):
        from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
        try:
            record = backend_utils.refresh_cluster_record(cluster_name)
        except exceptions.SkyTpuError:
            return None
        if record is None:
            return None
        return record['status']


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    parser.add_argument('--dag-yaml', type=str, required=True)
    args = parser.parse_args()
    JobsController(args.job_id, args.dag_yaml).run()


if __name__ == '__main__':
    main()
