"""Managed-job state machine + sqlite store (lives on the controller).

Parity: /root/reference/sky/jobs/state.py:151 (ManagedJobStatus) and its
spot_jobs sqlite schema.  One row per (job_id, task_id) so chain DAGs
report per-task progress.
"""
from __future__ import annotations

import enum
import os
import pathlib
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common_utils


class ManagedJobStatus(enum.Enum):
    PENDING = 'PENDING'
    SUBMITTED = 'SUBMITTED'
    STARTING = 'STARTING'
    RUNNING = 'RUNNING'
    RECOVERING = 'RECOVERING'
    SUCCEEDED = 'SUCCEEDED'
    CANCELLING = 'CANCELLING'
    CANCELLED = 'CANCELLED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    FAILED_PRECHECKS = 'FAILED_PRECHECKS'
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    def is_failed(self) -> bool:
        return self in _FAILED

    @classmethod
    def terminal_statuses(cls) -> List['ManagedJobStatus']:
        return list(_TERMINAL)

    def colored_str(self) -> str:
        color = {
            ManagedJobStatus.RUNNING: '\x1b[32m',
            ManagedJobStatus.SUCCEEDED: '\x1b[32m',
            ManagedJobStatus.RECOVERING: '\x1b[36m',
            ManagedJobStatus.CANCELLED: '\x1b[90m',
            ManagedJobStatus.CANCELLING: '\x1b[90m',
        }.get(self, '\x1b[33m' if not self.is_failed() else '\x1b[31m')
        return f'{color}{self.value}\x1b[0m'


_TERMINAL = {
    ManagedJobStatus.SUCCEEDED, ManagedJobStatus.CANCELLED,
    ManagedJobStatus.FAILED, ManagedJobStatus.FAILED_SETUP,
    ManagedJobStatus.FAILED_PRECHECKS, ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER,
}
_FAILED = {
    ManagedJobStatus.FAILED, ManagedJobStatus.FAILED_SETUP,
    ManagedJobStatus.FAILED_PRECHECKS, ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER,
}

_CREATE = """\
CREATE TABLE IF NOT EXISTS managed_jobs (
    job_id INTEGER,
    task_id INTEGER DEFAULT 0,
    job_name TEXT,
    task_name TEXT,
    status TEXT,
    submitted_at REAL,
    start_at REAL,
    end_at REAL,
    last_recovered_at REAL DEFAULT -1,
    recovery_count INTEGER DEFAULT 0,
    last_recovery_reason TEXT,
    failure_reason TEXT,
    cluster_name TEXT,
    run_timestamp TEXT,
    controller_pid INTEGER,
    dag_yaml_path TEXT,
    PRIMARY KEY (job_id, task_id)
)"""


def _db_path() -> str:
    path = os.environ.get('SKYTPU_MANAGED_JOB_DB')
    if path is None:
        path = os.path.join(common_utils.skytpu_home(), 'managed_jobs.db')
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    return path


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path(), timeout=10)
    conn.execute(_CREATE)
    _migrate(conn)
    return conn


def _migrate(conn: sqlite3.Connection) -> None:
    """Additive schema upgrades for DBs created before a column existed
    (CREATE IF NOT EXISTS never alters an existing table)."""
    cols = {row[1] for row in
            conn.execute('PRAGMA table_info(managed_jobs)')}
    if 'last_recovery_reason' not in cols:
        conn.execute('ALTER TABLE managed_jobs '
                     'ADD COLUMN last_recovery_reason TEXT')
    if 'batch_progress' not in cols:
        # Batch-infer drivers report ledger progress ("2/8 shards
        # (37/128 rows)") here; `jobs queue` renders it as PROGRESS.
        conn.execute('ALTER TABLE managed_jobs '
                     'ADD COLUMN batch_progress TEXT')


def allocate_job_id(job_name: str) -> int:
    """Atomically claim the next job id (a placeholder row for task 0 is
    inserted in the same write transaction, so concurrent launches can
    never claim the same id)."""
    with _conn() as conn:
        conn.execute(
            'INSERT INTO managed_jobs (job_id, task_id, job_name, '
            'status, submitted_at) '
            'SELECT COALESCE(MAX(job_id), 0) + 1, 0, ?, ?, ? '
            'FROM managed_jobs',
            (job_name, ManagedJobStatus.PENDING.value, time.time()))
        row = conn.execute(
            'SELECT MAX(job_id) FROM managed_jobs').fetchone()
        return row[0]


def submit_job(job_id: int, job_name: str, dag_yaml_path: str,
               task_names: List[str]) -> None:
    with _conn() as conn:
        for task_id, task_name in enumerate(task_names):
            conn.execute(
                'INSERT OR REPLACE INTO managed_jobs '
                '(job_id, task_id, job_name, task_name, status, '
                'submitted_at, dag_yaml_path) VALUES (?,?,?,?,?,?,?)',
                (job_id, task_id, job_name, task_name,
                 ManagedJobStatus.PENDING.value, time.time(),
                 dag_yaml_path))


def set_status(job_id: int, task_id: int, status: ManagedJobStatus,
               failure_reason: Optional[str] = None,
               last_recovery_reason: Optional[str] = None) -> None:
    sets = ['status=?']
    vals: List[Any] = [status.value]
    if status is ManagedJobStatus.RUNNING:
        sets.append('start_at=COALESCE(start_at, ?)')
        vals.append(time.time())
    if status.is_terminal():
        sets.append('end_at=?')
        vals.append(time.time())
    if failure_reason is not None:
        sets.append('failure_reason=?')
        vals.append(failure_reason)
    if last_recovery_reason is not None:
        # Terminal states reached through the recovery machinery (e.g.
        # restart-budget exhaustion) persist why, where `jobs queue`
        # surfaces it.
        sets.append('last_recovery_reason=?')
        vals.append(last_recovery_reason)
    vals += [job_id, task_id]
    with _conn() as conn:
        conn.execute(
            f'UPDATE managed_jobs SET {", ".join(sets)} '
            'WHERE job_id=? AND task_id=?', vals)


def set_recovering(job_id: int, task_id: int,
                   reason: Optional[str] = None) -> None:
    """Mark RECOVERING; `reason` persists WHY (preemption, user-code
    restart, …) so `jobs queue` can show it, not just that recovery is
    happening.  The attempt count is the incremented recovery_count."""
    sets = ['status=?', 'recovery_count=recovery_count+1',
            'last_recovered_at=?']
    vals: List[Any] = [ManagedJobStatus.RECOVERING.value, time.time()]
    if reason is not None:
        sets.append('last_recovery_reason=?')
        vals.append(reason)
    vals += [job_id, task_id]
    with _conn() as conn:
        conn.execute(
            f'UPDATE managed_jobs SET {", ".join(sets)} '
            'WHERE job_id=? AND task_id=?', vals)


def set_last_recovery_reason(job_id: int, task_id: int,
                             reason: str) -> None:
    """Refine WHY the current recovery is happening once the strategy
    has classified it (e.g. ``elastic_shrink(2→1)`` vs a full
    relaunch) — the controller records a generic reason at detection
    time, before the strategy knows whether it will resize or
    relaunch.  `jobs queue` REASON surfaces whichever wrote last."""
    with _conn() as conn:
        conn.execute(
            'UPDATE managed_jobs SET last_recovery_reason=? '
            'WHERE job_id=? AND task_id=?', (reason, job_id, task_id))


def set_batch_progress(job_id: int, task_id: int,
                       progress: str) -> None:
    """Record a batch-infer driver's ledger progress (shards/rows done
    vs total).  Written by the driver itself (it knows its job id from
    SKYTPU_MANAGED_JOB_ID) each time a shard commits; `jobs queue`
    surfaces it in the PROGRESS column — same plumbing as the
    recovery-reason column."""
    with _conn() as conn:
        conn.execute(
            'UPDATE managed_jobs SET batch_progress=? '
            'WHERE job_id=? AND task_id=?',
            (progress, job_id, task_id))


def set_cluster_name(job_id: int, task_id: int,
                     cluster_name: str) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE managed_jobs SET cluster_name=? '
            'WHERE job_id=? AND task_id=?', (cluster_name, job_id, task_id))


def set_controller_pid(job_id: int, pid: int) -> None:
    with _conn() as conn:
        conn.execute('UPDATE managed_jobs SET controller_pid=? '
                     'WHERE job_id=?', (pid, job_id))


def get_status(job_id: int) -> Optional[ManagedJobStatus]:
    """Aggregate status over the job's tasks (first non-terminal, else
    worst terminal)."""
    records = get_job_records(job_id)
    if not records:
        return None
    statuses = [ManagedJobStatus(r['status']) for r in records]
    for s in statuses:
        if not s.is_terminal():
            return s
    for s in statuses:
        if s.is_failed() or s is ManagedJobStatus.CANCELLED:
            return s
    return statuses[-1]


def get_job_records(job_id: Optional[int] = None) -> List[Dict[str, Any]]:
    query = 'SELECT * FROM managed_jobs'
    vals: tuple = ()
    if job_id is not None:
        query += ' WHERE job_id=?'
        vals = (job_id,)
    query += ' ORDER BY job_id DESC, task_id ASC'
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        rows = conn.execute(query, vals).fetchall()
    return [dict(r) for r in rows]


def get_nonterminal_job_ids() -> List[int]:
    terminal = [s.value for s in ManagedJobStatus.terminal_statuses()]
    q = ','.join('?' * len(terminal))
    with _conn() as conn:
        rows = conn.execute(
            f'SELECT DISTINCT job_id FROM managed_jobs '
            f'WHERE status NOT IN ({q})', terminal).fetchall()
    return [r[0] for r in rows]
