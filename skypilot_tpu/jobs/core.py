"""Managed-jobs client API: launch / queue / cancel / tail_logs.

Parity: /root/reference/sky/jobs/core.py:33 (launch wraps the user DAG
into a controller task).  Controller placement is configurable
(jobs.constants):

- 'process' (default): the per-job controller runs as a detached local
  daemon — hermetic, no extra VM, same supervision semantics.
- 'cluster': a controller cluster is launched through the normal stack
  and runs the identical controller module (reference behavior with the
  controller VM; the task ships the DAG YAML as a file mount).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.jobs import constants
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import dag_utils

logger = sky_logging.init_logger(__name__)


def _dag_yaml_dir() -> str:
    return common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'managed_jobs'))


def launch(entrypoint: Union[task_lib.Task, 'Any'],
           name: Optional[str] = None,
           *,
           detach_run: bool = True) -> int:
    """Submit a managed job; returns the managed job id.

    The DAG may be a chain (task_a >> task_b); each task runs on its own
    cluster under the controller's supervision.
    """
    dag = dag_utils.convert_entrypoint_to_dag(entrypoint)
    if not dag.is_chain():
        raise exceptions.InvalidTaskError(
            'Managed jobs support single tasks or chain DAGs.')
    job_name = name or dag.name or dag.tasks[0].name or 'managed-job'

    for task in dag.tasks:
        task._validate()  # pylint: disable=protected-access

    mode = config_lib.get_nested(constants.CONTROLLER_MODE_KEY,
                                 constants.DEFAULT_CONTROLLER_MODE)
    if mode == 'cluster':
        # The controller cluster cannot see this machine's filesystem:
        # rewrite local workdir/file_mounts into auto-bucket storage
        # mounts and upload now (reference controller_utils.py:679).
        from skypilot_tpu.utils import controller_utils  # pylint: disable=import-outside-toplevel
        for task in dag.tasks:
            controller_utils.maybe_translate_local_file_mounts_and_sync_up(
                task, task_type='jobs')

    job_id = state.allocate_job_id(job_name)
    yaml_path = os.path.join(_dag_yaml_dir(), f'{job_name}-{job_id}.yaml')
    dag_utils.dump_chain_dag_to_yaml(dag, yaml_path)
    state.submit_job(job_id, job_name, yaml_path,
                     [t.name or f'task-{i}'
                      for i, t in enumerate(dag.tasks)])
    state.set_status(job_id, 0, state.ManagedJobStatus.SUBMITTED)
    if mode == 'process':
        _start_controller_process(job_id, yaml_path)
    elif mode == 'cluster':
        _launch_controller_cluster(job_id, job_name, yaml_path)
    else:
        raise exceptions.InvalidSkyTpuConfigError(
            f'jobs.controller.mode must be process|cluster, got {mode!r}')

    logger.info(f'Managed job {job_id} ({job_name}) submitted '
                f'(controller mode: {mode}).')
    if not detach_run:
        _wait_for_terminal(job_id)
    return job_id


def _start_controller_process(job_id: int, yaml_path: str) -> None:
    env = dict(os.environ)
    env[constants.ENV_MANAGED_JOB_ID] = str(job_id)
    log_dir = common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'managed_jobs', 'logs'))
    log_path = os.path.join(log_dir, f'controller-{job_id}.log')
    with open(log_path, 'ab') as log_f:
        proc = subprocess.Popen(  # pylint: disable=consider-using-with
            [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
             '--job-id', str(job_id), '--dag-yaml', yaml_path],
            stdout=log_f, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, env=env,
            start_new_session=True)
    from skypilot_tpu.utils import daemon_registry  # pylint: disable=import-outside-toplevel
    daemon_registry.register(proc.pid, 'jobs-controller')
    state.set_controller_pid(job_id, proc.pid)


def _launch_controller_cluster(job_id: int, job_name: str,
                               yaml_path: str) -> None:
    from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
    from skypilot_tpu import resources as resources_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.skylet import constants as skylet_constants  # pylint: disable=import-outside-toplevel
    remote_yaml = f'~/.skytpu/managed_jobs/{job_name}-{job_id}.yaml'
    controller_task = task_lib.Task(
        name=f'jobs-controller-{job_id}',
        run=(f'PYTHONPATH={skylet_constants.SKY_REMOTE_APP_DIR}'
             f':$PYTHONPATH {skylet_constants.SKY_PYTHON_CMD} '
             f'-m skypilot_tpu.jobs.controller '
             f'--job-id {job_id} --dag-yaml {remote_yaml}'),
        file_mounts={remote_yaml: yaml_path},
    )
    controller_task.set_resources(
        resources_lib.Resources(cpus='4+', memory='8+'))
    execution.launch(controller_task,
                     cluster_name=constants.CONTROLLER_CLUSTER_NAME,
                     stream_logs=False, detach_run=True)


def _wait_for_terminal(job_id: int, poll: float = 2.0) -> None:
    while True:
        status = state.get_status(job_id)
        if status is None or status.is_terminal():
            return
        time.sleep(poll)


def queue(refresh: bool = False,
          job_ids: Optional[List[int]] = None) -> List[Dict[str, Any]]:
    """All managed-job records (newest first).

    Parity: reference jobs/core.py queue().  In 'cluster' controller
    mode the state db lives on the controller cluster, so the query
    routes there over ssh codegen (ManagedJobCodeGen).
    """
    del refresh  # the controller writes state continuously
    records = _query_records()
    if job_ids is not None:
        records = [r for r in records if r['job_id'] in job_ids]
    return records


def _query_records() -> List[Dict[str, Any]]:
    from skypilot_tpu.jobs import utils as jobs_utils  # pylint: disable=import-outside-toplevel
    if jobs_utils.controller_mode() == 'cluster':
        return jobs_utils.run_on_controller_cluster(
            jobs_utils.ManagedJobCodeGen.queue(), 'MJOBS:')
    return state.get_job_records()


def cancel(job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    """Request cancellation; the controller tears down the task cluster
    and marks CANCELLED."""
    from skypilot_tpu.jobs import utils as jobs_utils  # pylint: disable=import-outside-toplevel
    if (jobs_utils.controller_mode() == 'cluster' and
            os.environ.get('SKYTPU_ON_CONTROLLER') != '1'):
        return jobs_utils.run_on_controller_cluster(
            jobs_utils.ManagedJobCodeGen.cancel(job_ids, all_jobs),
            'MCANCELLED:')
    if all_jobs:
        job_ids = state.get_nonterminal_job_ids()
    if not job_ids:
        return []
    cancelled = []
    for job_id in job_ids:
        status = state.get_status(job_id)
        if status is None or status.is_terminal():
            continue
        for rec in state.get_job_records(job_id):
            if not state.ManagedJobStatus(rec['status']).is_terminal():
                state.set_status(job_id, rec['task_id'],
                                 state.ManagedJobStatus.CANCELLING)
        cancelled.append(job_id)
    return cancelled


def tail_logs(job_id: Optional[int] = None, follow: bool = True) -> None:
    """Tail the job's task-cluster logs (falls back to the controller
    log before the first cluster exists)."""
    from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
    if job_id is None:
        ids = [r['job_id'] for r in state.get_job_records()]
        if not ids:
            raise exceptions.ManagedJobStatusError('No managed jobs.')
        job_id = max(ids)
    records = state.get_job_records(job_id)
    if not records:
        raise exceptions.ManagedJobStatusError(
            f'No managed job with id {job_id}.')
    active = [r for r in records if r['cluster_name']]
    if active:
        rec = active[-1]
        try:
            core.tail_logs(rec['cluster_name'], follow=follow)
            return
        except exceptions.SkyTpuError:
            pass
    log_path = os.path.join(common_utils.skytpu_home(), 'managed_jobs',
                            'logs', f'controller-{job_id}.log')
    if os.path.exists(log_path):
        with open(log_path, encoding='utf-8', errors='replace') as f:
            print(f.read(), end='')
