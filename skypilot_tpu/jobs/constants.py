"""Managed-jobs constants."""

# Controller placement: 'process' runs the per-job controller as a local
# daemon process (hermetic, no extra VM); 'cluster' launches a controller
# cluster via the normal stack (parity with the reference's controller-VM
# design, /root/reference/sky/jobs/core.py:33).
CONTROLLER_MODE_KEY = ('jobs', 'controller', 'mode')
DEFAULT_CONTROLLER_MODE = 'process'

CONTROLLER_CLUSTER_NAME = 'skytpu-jobs-controller'

# Seconds between monitor-loop status checks
# (parity: reference jobs/utils.py JOB_STATUS_CHECK_GAP_SECONDS).
JOB_STATUS_CHECK_GAP_SECONDS = 20.0
# Initial delay before the first status check after (re)launch.
JOB_STARTED_CHECK_GAP_SECONDS = 5.0

ENV_MANAGED_JOB_ID = 'SKYTPU_MANAGED_JOB_ID'
