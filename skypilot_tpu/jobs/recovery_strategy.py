"""Recovery strategies: how a managed job's cluster is (re)launched.

Parity: /root/reference/sky/jobs/recovery_strategy.py
(StrategyExecutor.make registry :63-126, FAILOVER :395,
EAGER_NEXT_REGION :483).  TPU-first: before any relaunch of a
preempted/broken slice the old capacity is *terminated* — a preempted
TPU-VM lingers in an unusable state and a multi-host slice fails as a
unit (reference cleans up spot TPUs specially, gcp.py:928-934; here it
is the default for every recovery).
"""
from __future__ import annotations

import time
import typing
from typing import Dict, Optional, Type

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.jobs import constants
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.utils import common_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

RECOVERY_STRATEGIES: Dict[str, Type['StrategyExecutor']] = {}
DEFAULT_RECOVERY_STRATEGY = 'EAGER_NEXT_REGION'

# Max consecutive launch failures before giving up a recovery attempt
# entirely (parity: reference MAX_JOB_CHECKING_RETRY).
_MAX_LAUNCH_RETRY = 3
_RETRY_GAP_SECONDS = 2.0


def _register(name: str):

    def deco(cls):
        RECOVERY_STRATEGIES[name] = cls
        cls.NAME = name
        return cls

    return deco


class StrategyExecutor:
    """Launch / recover one task's cluster."""

    NAME = 'base'

    def __init__(self, cluster_name: str, task: 'task_lib.Task',
                 retry_until_up: bool = True,
                 max_restarts_on_errors: int = 0,
                 job_id: Optional[int] = None,
                 task_id: int = 0) -> None:
        self.cluster_name = cluster_name
        self.task = task
        self.retry_until_up = retry_until_up
        self.max_restarts_on_errors = max_restarts_on_errors
        self.restart_count_on_errors = 0
        # Managed-job identity for the flight recorder; None when the
        # executor is used outside a managed job (journaling off).
        self.job_id = job_id
        self.task_id = task_id
        self.recovery_attempts = 0
        # Where the previous successful launch landed (region/zone),
        # captured at launch time — the cluster record is gone by the
        # time a recovery wants to prefer the same region.
        self._last_region: Optional[str] = None
        self._last_zone: Optional[str] = None

    @classmethod
    def make(cls, cluster_name: str, task: 'task_lib.Task',
             job_id: Optional[int] = None,
             task_id: int = 0) -> 'StrategyExecutor':
        """Pick the strategy from the task's resources.job_recovery."""
        names = set()
        for resources in task.resources:
            recovery = resources.job_recovery
            if recovery:
                names.add(str(recovery).upper())
        if len(names) > 1:
            raise exceptions.InvalidTaskError(
                f'All resources options must share one job_recovery '
                f'strategy, got {sorted(names)}')
        name = names.pop() if names else DEFAULT_RECOVERY_STRATEGY
        if name not in RECOVERY_STRATEGIES:
            raise exceptions.InvalidTaskError(
                f'Unknown job_recovery strategy {name!r}; have '
                f'{sorted(RECOVERY_STRATEGIES)}')
        return RECOVERY_STRATEGIES[name](cluster_name, task,
                                         job_id=job_id, task_id=task_id)

    def _journal(self) -> Optional['events_lib.EventJournal']:
        if self.job_id is None:
            return None
        return events_lib.job_journal(self.job_id)

    # ------------------------------------------------------------ launch

    def launch(self) -> Optional[int]:
        """First launch; returns the job id on the task cluster."""
        return self._launch(prefer_same_region=False)

    def recover(self) -> Optional[int]:
        """Tear down broken capacity, then relaunch per strategy.

        Template method: journals the recovery attempt (start/end with
        duration + status) and feeds `skytpu_jobs_recovery_seconds`;
        the strategy-specific relaunch policy lives in `_do_recover`.
        """
        self.recovery_attempts += 1
        journal = self._journal()
        t0 = time.monotonic()
        if journal is not None:
            journal.append('recovery_start', job_id=self.job_id,
                           task_id=self.task_id,
                           attempt=self.recovery_attempts,
                           strategy=self.NAME,
                           cluster=self.cluster_name)
        try:
            # Chaos site: a raise here fails this recovery attempt the
            # same way a real relaunch failure would (journaled as the
            # recovery_end status below).
            chaos_injector.inject('jobs.recover', job_id=self.job_id,
                                  cluster=self.cluster_name,
                                  attempt=self.recovery_attempts,
                                  strategy=self.NAME)
            remote_job_id = self._do_recover()
        except Exception as e:
            if journal is not None:
                journal.append(
                    'recovery_end', job_id=self.job_id,
                    task_id=self.task_id,
                    attempt=self.recovery_attempts, status=type(e).__name__,
                    error=str(e)[:500],
                    duration_s=round(time.monotonic() - t0, 6))
            raise
        duration = time.monotonic() - t0
        events_lib.jobs_recovery_hist().observe(duration)
        if journal is not None:
            journal.append('recovery_end', job_id=self.job_id,
                           task_id=self.task_id,
                           attempt=self.recovery_attempts, status='ok',
                           remote_job_id=remote_job_id,
                           duration_s=round(duration, 6))
        return remote_job_id

    def _do_recover(self) -> Optional[int]:
        """Strategy-specific relaunch policy."""
        raise NotImplementedError

    def cleanup_cluster(self) -> None:
        """Terminate the task cluster (idempotent; slices are
        all-or-nothing so partial teardown is never kept)."""
        from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
        try:
            core.down(self.cluster_name)
        except (exceptions.ClusterNotUpError, ValueError):
            pass
        except exceptions.SkyTpuError as e:
            logger.warning(
                f'cleanup of {self.cluster_name} failed (will still '
                f'relaunch): {common_utils.format_exception(e)}')

    def _record_launch_location(self) -> None:
        """Remember where the launch landed, for prefer_same_region
        recoveries (the cluster record does not survive cleanup)."""
        from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
        try:
            record = global_user_state.get_cluster_from_name(
                self.cluster_name)
        except Exception:  # pylint: disable=broad-except
            return
        if record is None or record.get('handle') is None:
            return
        launched = getattr(record['handle'], 'launched_resources', None)
        if launched is not None:
            self._last_region = launched.region
            self._last_zone = launched.zone

    def _pin_resources(self):
        """The task's resources pinned to the previous launch's
        region/zone — the optimizer then searches only the capacity
        pool the slice just ran in (cheap if the outage was
        transient)."""
        return type(self.task.resources)(
            r.copy(region=self._last_region, zone=self._last_zone)
            for r in self.task.resources)

    def _launch(self, prefer_same_region: bool,
                raise_on_failure: bool = True) -> Optional[int]:
        from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
        journal = self._journal()
        backoff = common_utils.Backoff(_RETRY_GAP_SECONDS)
        original_resources = self.task.resources
        if prefer_same_region and self._last_region is not None:
            # Pin the optimizer to the previous launch's region/zone
            # for this attempt; the pin is dropped (resources restored)
            # before any fallback attempt re-searches the full space.
            self.task.set_resources(self._pin_resources())
        try:
            for attempt in range(_MAX_LAUNCH_RETRY):
                try:
                    job_id = execution.launch(
                        self.task, cluster_name=self.cluster_name,
                        stream_logs=False, detach_run=True,
                        retry_until_up=self.retry_until_up)
                    self._record_launch_location()
                    if journal is not None:
                        journal.append('launch_attempt',
                                       job_id=self.job_id,
                                       task_id=self.task_id,
                                       attempt=attempt + 1, status='ok',
                                       cluster=self.cluster_name)
                    return job_id
                except exceptions.ResourcesUnavailableError as e:
                    if journal is not None:
                        journal.append('launch_attempt',
                                       job_id=self.job_id,
                                       task_id=self.task_id,
                                       attempt=attempt + 1, status='fail',
                                       cluster=self.cluster_name,
                                       error=str(e)[:500])
                    if (raise_on_failure and
                            attempt == _MAX_LAUNCH_RETRY - 1):
                        raise
                    logger.info(f'launch attempt {attempt + 1} failed: '
                                f'{common_utils.format_exception(e)}')
                    # (current_backoff is a property — calling it was a
                    # latent crash on every real launch retry.)
                    time.sleep(backoff.current_backoff)
            return None
        finally:
            self.task.set_resources(original_resources)


@_register('EAGER_NEXT_REGION')
class EagerNextRegionStrategy(StrategyExecutor):
    """On recovery, immediately re-optimize across regions/zones (the
    preempting region is likely still capacity-starved).  Default —
    parity: reference recovery_strategy.py:483."""

    def _do_recover(self) -> Optional[int]:
        self.cleanup_cluster()
        # Drop any region/zone pinning learned from the previous launch
        # so the optimizer searches the full space again.
        return self._launch(prefer_same_region=False)


@_register('FAILOVER')
class FailoverStrategy(StrategyExecutor):
    """On recovery, first retry in the same region (cheap if transient),
    then fall back to the full search.  Parity: reference
    recovery_strategy.py:395."""

    def _do_recover(self) -> Optional[int]:
        self.cleanup_cluster()
        job_id = self._launch(prefer_same_region=True,
                              raise_on_failure=False)
        if job_id is not None:
            return job_id
        return self._launch(prefer_same_region=False)


@_register('ELASTIC')
class ElasticStrategy(StrategyExecutor):
    """Recovery = resize, not restart.

    On a PARTIAL preemption (some hosts of the slice reclaimed, the
    rest alive — the gang supervisor's abort reports the dead ranks,
    the provider query shows the mixed host state), the gang shrinks to
    the surviving hosts: dead hosts are trimmed from the cluster, the
    task is re-exec'd on the survivors (no teardown, no re-provision),
    and the task resumes from the checkpoint contract onto a smaller
    mesh (models/elastic.py).  When capacity returns, a later recovery
    EXPANDS back to the full slice via a full-size relaunch.  Full
    evictions (nothing survives) fall back to the eager relaunch.

    Every resize is journaled ``gang_resize{from,to}`` and persisted as
    ``last_recovery_reason=elastic_shrink(n→m)`` / ``elastic_expand``
    so `jobs queue` post-mortems distinguish resize from relaunch, and
    the PR 4 recovery-seconds histograms price each path.
    """

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        # Full-size host count, learned from the live cluster; set once
        # a shrink happens so a later recovery knows what to expand to.
        self._full_hosts: Optional[int] = None
        self._current_hosts: Optional[int] = None

    # ------------------------------------------------------------ helpers

    def _provider_name(self) -> Optional[str]:
        from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
        record = global_user_state.get_cluster_from_name(self.cluster_name)
        if record is None or record.get('handle') is None:
            return None
        return record['handle'].provider_name

    def _surviving_hosts(self) -> tuple:
        """(alive, total) from the provider's live view; (0, 0) when
        the cluster is gone entirely."""
        from skypilot_tpu import provision  # pylint: disable=import-outside-toplevel
        from skypilot_tpu import status_lib  # pylint: disable=import-outside-toplevel
        provider = self._provider_name()
        if provider is None:
            return 0, 0
        try:
            statuses = provision.query_instances(provider,
                                                 self.cluster_name)
        except Exception:  # pylint: disable=broad-except
            return 0, 0
        alive = sum(1 for s in statuses.values()
                    if s is status_lib.ClusterStatus.UP)
        return alive, len(statuses)

    def _set_reason(self, reason: str) -> None:
        if self.job_id is None:
            return
        from skypilot_tpu.jobs import state  # pylint: disable=import-outside-toplevel
        state.set_last_recovery_reason(self.job_id, self.task_id, reason)

    def _journal_resize(self, old: int, new: int, direction: str) -> None:
        events_lib.gang_resizes().labels(direction=direction).inc()
        journal = self._journal()
        if journal is not None:
            journal.append('gang_resize', **{'from': old, 'to': new},
                           job_id=self.job_id, task_id=self.task_id,
                           direction=direction,
                           cluster=self.cluster_name)

    # ------------------------------------------------------------ recover

    def _do_recover(self) -> Optional[int]:
        alive, total = self._surviving_hosts()
        if 0 < alive < total:
            try:
                return self._shrink(alive, total)
            except exceptions.SkyTpuError as e:
                logger.warning(
                    f'elastic shrink of {self.cluster_name} failed '
                    f'({common_utils.format_exception(e)}); falling '
                    f'back to full relaunch')
            except NotImplementedError:
                logger.info(
                    f'{self.cluster_name}: provider has no partial-loss '
                    f'semantics; falling back to full relaunch')
        return self._relaunch_full()

    def _shrink(self, alive: int, total: int) -> Optional[int]:
        """Trim dead hosts and re-exec on the survivors — the task
        resumes from its checkpoint onto the smaller gang."""
        from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
        from skypilot_tpu import global_user_state  # pylint: disable=import-outside-toplevel
        from skypilot_tpu import provision  # pylint: disable=import-outside-toplevel
        from skypilot_tpu import status_lib  # pylint: disable=import-outside-toplevel
        provider = self._provider_name()
        if provider is None:
            raise exceptions.ClusterNotUpError(
                f'{self.cluster_name} has no handle')
        survivors = provision.trim_instances(provider, self.cluster_name)
        # The drift matrix marked the mixed-state cluster INIT; after
        # the trim the surviving hosts ARE the (smaller) healthy
        # cluster, runtime intact.
        global_user_state.set_cluster_status(self.cluster_name,
                                             status_lib.ClusterStatus.UP)
        if self._full_hosts is None:
            self._full_hosts = total
        self._current_hosts = survivors
        self._journal_resize(total, survivors, 'shrink')
        self._set_reason(f'elastic_shrink({total}→{survivors})')
        logger.info(f'elastic shrink: {self.cluster_name} '
                    f'{total} -> {survivors} host(s); resuming from '
                    f'checkpoint on the survivors')
        return execution.exec(self.task, cluster_name=self.cluster_name,
                              stream_logs=False, detach_run=True)

    def _relaunch_full(self) -> Optional[int]:
        """Full relaunch at the originally-requested size.  While
        shrunk, this IS the expand path: capacity returning lets the
        provision land the full slice again."""
        expanding = (self._full_hosts is not None and
                     self._current_hosts is not None and
                     self._current_hosts < self._full_hosts)
        self.cleanup_cluster()
        job_id = self._launch(prefer_same_region=False)
        if expanding:
            self._journal_resize(self._current_hosts, self._full_hosts,
                                 'expand')
            self._set_reason(f'elastic_expand({self._current_hosts}→'
                             f'{self._full_hosts})')
            logger.info(f'elastic expand: {self.cluster_name} '
                        f'{self._current_hosts} -> {self._full_hosts} '
                        f'host(s)')
        # A full relaunch lands the originally-requested size either way.
        self._current_hosts = self._full_hosts
        return job_id
