"""Recovery strategies: how a managed job's cluster is (re)launched.

Parity: /root/reference/sky/jobs/recovery_strategy.py
(StrategyExecutor.make registry :63-126, FAILOVER :395,
EAGER_NEXT_REGION :483).  TPU-first: before any relaunch of a
preempted/broken slice the old capacity is *terminated* — a preempted
TPU-VM lingers in an unusable state and a multi-host slice fails as a
unit (reference cleans up spot TPUs specially, gcp.py:928-934; here it
is the default for every recovery).
"""
from __future__ import annotations

import time
import typing
from typing import Dict, Optional, Type

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.jobs import constants
from skypilot_tpu.observability import events as events_lib
from skypilot_tpu.utils import common_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

RECOVERY_STRATEGIES: Dict[str, Type['StrategyExecutor']] = {}
DEFAULT_RECOVERY_STRATEGY = 'EAGER_NEXT_REGION'

# Max consecutive launch failures before giving up a recovery attempt
# entirely (parity: reference MAX_JOB_CHECKING_RETRY).
_MAX_LAUNCH_RETRY = 3
_RETRY_GAP_SECONDS = 2.0


def _register(name: str):

    def deco(cls):
        RECOVERY_STRATEGIES[name] = cls
        cls.NAME = name
        return cls

    return deco


class StrategyExecutor:
    """Launch / recover one task's cluster."""

    NAME = 'base'

    def __init__(self, cluster_name: str, task: 'task_lib.Task',
                 retry_until_up: bool = True,
                 max_restarts_on_errors: int = 0,
                 job_id: Optional[int] = None,
                 task_id: int = 0) -> None:
        self.cluster_name = cluster_name
        self.task = task
        self.retry_until_up = retry_until_up
        self.max_restarts_on_errors = max_restarts_on_errors
        self.restart_count_on_errors = 0
        # Managed-job identity for the flight recorder; None when the
        # executor is used outside a managed job (journaling off).
        self.job_id = job_id
        self.task_id = task_id
        self.recovery_attempts = 0

    @classmethod
    def make(cls, cluster_name: str, task: 'task_lib.Task',
             job_id: Optional[int] = None,
             task_id: int = 0) -> 'StrategyExecutor':
        """Pick the strategy from the task's resources.job_recovery."""
        names = set()
        for resources in task.resources:
            recovery = resources.job_recovery
            if recovery:
                names.add(str(recovery).upper())
        if len(names) > 1:
            raise exceptions.InvalidTaskError(
                f'All resources options must share one job_recovery '
                f'strategy, got {sorted(names)}')
        name = names.pop() if names else DEFAULT_RECOVERY_STRATEGY
        if name not in RECOVERY_STRATEGIES:
            raise exceptions.InvalidTaskError(
                f'Unknown job_recovery strategy {name!r}; have '
                f'{sorted(RECOVERY_STRATEGIES)}')
        return RECOVERY_STRATEGIES[name](cluster_name, task,
                                         job_id=job_id, task_id=task_id)

    def _journal(self) -> Optional['events_lib.EventJournal']:
        if self.job_id is None:
            return None
        return events_lib.job_journal(self.job_id)

    # ------------------------------------------------------------ launch

    def launch(self) -> Optional[int]:
        """First launch; returns the job id on the task cluster."""
        return self._launch(prefer_same_region=False)

    def recover(self) -> Optional[int]:
        """Tear down broken capacity, then relaunch per strategy.

        Template method: journals the recovery attempt (start/end with
        duration + status) and feeds `skytpu_jobs_recovery_seconds`;
        the strategy-specific relaunch policy lives in `_do_recover`.
        """
        self.recovery_attempts += 1
        journal = self._journal()
        t0 = time.monotonic()
        if journal is not None:
            journal.append('recovery_start', job_id=self.job_id,
                           task_id=self.task_id,
                           attempt=self.recovery_attempts,
                           strategy=self.NAME,
                           cluster=self.cluster_name)
        try:
            # Chaos site: a raise here fails this recovery attempt the
            # same way a real relaunch failure would (journaled as the
            # recovery_end status below).
            chaos_injector.inject('jobs.recover', job_id=self.job_id,
                                  cluster=self.cluster_name,
                                  attempt=self.recovery_attempts,
                                  strategy=self.NAME)
            remote_job_id = self._do_recover()
        except Exception as e:
            if journal is not None:
                journal.append(
                    'recovery_end', job_id=self.job_id,
                    task_id=self.task_id,
                    attempt=self.recovery_attempts, status=type(e).__name__,
                    error=str(e)[:500],
                    duration_s=round(time.monotonic() - t0, 6))
            raise
        duration = time.monotonic() - t0
        events_lib.jobs_recovery_hist().observe(duration)
        if journal is not None:
            journal.append('recovery_end', job_id=self.job_id,
                           task_id=self.task_id,
                           attempt=self.recovery_attempts, status='ok',
                           remote_job_id=remote_job_id,
                           duration_s=round(duration, 6))
        return remote_job_id

    def _do_recover(self) -> Optional[int]:
        """Strategy-specific relaunch policy."""
        raise NotImplementedError

    def cleanup_cluster(self) -> None:
        """Terminate the task cluster (idempotent; slices are
        all-or-nothing so partial teardown is never kept)."""
        from skypilot_tpu import core  # pylint: disable=import-outside-toplevel
        try:
            core.down(self.cluster_name)
        except (exceptions.ClusterNotUpError, ValueError):
            pass
        except exceptions.SkyTpuError as e:
            logger.warning(
                f'cleanup of {self.cluster_name} failed (will still '
                f'relaunch): {common_utils.format_exception(e)}')

    def _launch(self, prefer_same_region: bool,
                raise_on_failure: bool = True) -> Optional[int]:
        from skypilot_tpu import execution  # pylint: disable=import-outside-toplevel
        del prefer_same_region  # used by subclasses via task mutation
        journal = self._journal()
        backoff = common_utils.Backoff(_RETRY_GAP_SECONDS)
        for attempt in range(_MAX_LAUNCH_RETRY):
            try:
                job_id = execution.launch(
                    self.task, cluster_name=self.cluster_name,
                    stream_logs=False, detach_run=True,
                    retry_until_up=self.retry_until_up)
                if journal is not None:
                    journal.append('launch_attempt', job_id=self.job_id,
                                   task_id=self.task_id,
                                   attempt=attempt + 1, status='ok',
                                   cluster=self.cluster_name)
                return job_id
            except exceptions.ResourcesUnavailableError as e:
                if journal is not None:
                    journal.append('launch_attempt', job_id=self.job_id,
                                   task_id=self.task_id,
                                   attempt=attempt + 1, status='fail',
                                   cluster=self.cluster_name,
                                   error=str(e)[:500])
                if raise_on_failure and attempt == _MAX_LAUNCH_RETRY - 1:
                    raise
                logger.info(f'launch attempt {attempt + 1} failed: '
                            f'{common_utils.format_exception(e)}')
                time.sleep(backoff.current_backoff())
        return None


@_register('EAGER_NEXT_REGION')
class EagerNextRegionStrategy(StrategyExecutor):
    """On recovery, immediately re-optimize across regions/zones (the
    preempting region is likely still capacity-starved).  Default —
    parity: reference recovery_strategy.py:483."""

    def _do_recover(self) -> Optional[int]:
        self.cleanup_cluster()
        # Drop any region/zone pinning learned from the previous launch
        # so the optimizer searches the full space again.
        return self._launch(prefer_same_region=False)


@_register('FAILOVER')
class FailoverStrategy(StrategyExecutor):
    """On recovery, first retry in the same region (cheap if transient),
    then fall back to the full search.  Parity: reference
    recovery_strategy.py:395."""

    def _do_recover(self) -> Optional[int]:
        self.cleanup_cluster()
        job_id = self._launch(prefer_same_region=True,
                              raise_on_failure=False)
        if job_id is not None:
            return job_id
        return self._launch(prefer_same_region=False)
