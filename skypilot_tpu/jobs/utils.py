"""Managed-jobs codegen: client↔controller-cluster RPC over ssh.

Parity: /root/reference/sky/jobs/utils.py ManagedJobCodeGen — when the
controller runs on its own cluster (jobs.controller.mode: cluster), the
managed-job state db lives THERE; queue/cancel route through these
generated one-liners executed on the controller cluster's head, exactly
like the skylet JobLibCodeGen transport.
"""
from __future__ import annotations

import shlex
from typing import Any, Dict, List, Optional

from skypilot_tpu.skylet import constants


class ManagedJobCodeGen:

    _PREFIX = ('import json, os; '
               "os.environ.setdefault('PYTHONUNBUFFERED','1'); "
               'from skypilot_tpu.jobs import state')

    @classmethod
    def _build(cls, code: List[str]) -> str:
        full = '; '.join([cls._PREFIX] + code)
        python = constants.SKY_PYTHON_CMD
        app_dir = constants.SKY_REMOTE_APP_DIR
        return (f'PYTHONPATH={app_dir}:$PYTHONPATH {python} -u -c '
                f'{shlex.quote(full)}')

    @classmethod
    def queue(cls) -> str:
        return cls._build([
            'records = state.get_job_records()',
            'print("MJOBS:" + json.dumps(records), flush=True)',
        ])

    @classmethod
    def cancel(cls, job_ids: Optional[List[int]],
               all_jobs: bool = False) -> str:
        return cls._build([
            # Marker breaks the cluster-mode recursion: on the
            # controller, cancel() must act on the local state db.
            "os.environ['SKYTPU_ON_CONTROLLER'] = '1'",
            'from skypilot_tpu.jobs import core',
            f'cancelled = core.cancel({job_ids!r}, all_jobs={all_jobs})',
            'print("MCANCELLED:" + json.dumps(cancelled), flush=True)',
        ])


def run_on_controller_cluster(code: str, tag: str) -> Any:
    """Execute codegen on the controller cluster's head; parse the
    tagged JSON line."""
    from skypilot_tpu.backends import backend_utils  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.jobs import constants as jobs_constants  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.skylet import job_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.utils import subprocess_utils  # pylint: disable=import-outside-toplevel
    handle = backend_utils.check_cluster_available(
        jobs_constants.CONTROLLER_CLUSTER_NAME)
    head = handle.get_command_runners()[0]
    rc, stdout, stderr = head.run(code, require_outputs=True,
                                  stream_logs=False)
    subprocess_utils.handle_returncode(
        rc, code, 'Failed to reach the jobs controller cluster.', stderr)
    return job_lib.parse_tagged_json(stdout, tag)


def controller_mode() -> str:
    from skypilot_tpu import config as config_lib  # pylint: disable=import-outside-toplevel
    from skypilot_tpu.jobs import constants as jobs_constants  # pylint: disable=import-outside-toplevel
    return config_lib.get_nested(jobs_constants.CONTROLLER_MODE_KEY,
                                 jobs_constants.DEFAULT_CONTROLLER_MODE)
