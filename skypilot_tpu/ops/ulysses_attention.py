"""Ulysses-style all-to-all sequence parallelism (PAPERS.md: DeepSpeed-
Ulysses pattern, re-built on XLA collectives).

The alternative long-context strategy to ring attention (SURVEY.md
§2.3 mandates "ring attention or all-to-all sequence/context
parallelism"; this framework ships both):

- ring: k/v chunks rotate around the ICI ring, P hops, per-hop flash +
  logaddexp merge.  Communication scales with k/v size only; works for
  any head count.
- ulysses (this module): two `all_to_all`s re-shard the SEQUENCE axis
  into the HEAD axis — each device then holds h/P full-sequence heads
  and runs ONE ordinary causal flash kernel, then the output is
  re-sharded back.  Cheaper compute structure (no per-hop switch, no
  merge math, exact flash numerics) and the collectives are single
  fused all-to-alls on ICI; requires num_heads % ring_size == 0.

Choose per layer via ModelConfig.sequence_parallel ('ring'|'ulysses').
Differentiable end-to-end (all_to_all transposes to all_to_all).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax

from skypilot_tpu.ops import sp_common
from skypilot_tpu.ops.attention import flash_attention


def _ulysses_attention_sharded(q, k, v, *, axis_name: str,
                               sm_scale: float, causal: bool,
                               block_q: int, block_k: int):
    """Body under shard_map: q/k/v are [b, h, s/P, d] local chunks.

    all_to_all(split heads → concat seq) yields [b, h/P, s, d]: every
    device attends h/P heads over the FULL sequence, so plain causal
    flash is exact — seq chunks concatenate in device order, preserving
    global positions.

    Safe to call directly from inside an existing manual region (the
    PP x SP path): divisibility is re-checked here against the axis
    size — `psum(1, axis)` is concrete under shard_map — and GQA kv
    heads are broadcast up when they don't divide.
    """
    sp = jax.lax.psum(1, axis_name)
    if q.shape[1] % sp:
        raise ValueError(
            f'ulysses needs num_heads ({q.shape[1]}) divisible by the '
            f'{axis_name!r} axis ({sp}); use ring attention instead.')
    k, v = sp_common.broadcast_gqa_if_indivisible(q, k, v, sp)
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    # [b, h, s/P, d] -> [b, h/P, s, d]
    qh = a2a(q, split_axis=1, concat_axis=2)
    kh = a2a(k, split_axis=1, concat_axis=2)
    vh = a2a(v, split_axis=1, concat_axis=2)
    out = flash_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k)
    # [b, h/P, s, d] -> [b, h, s/P, d]
    return a2a(out, split_axis=2, concat_axis=1)


def ulysses_attention(q, k, v, *, mesh, axis_name: str = 'sequence',
                      causal: bool = True,
                      sm_scale: Optional[float] = None,
                      block_q: int = 128, block_k: int = 128):
    """All-to-all sequence-parallel attention.

    Args:
      q, k, v: [batch, heads, seq, head_dim] GLOBAL arrays (seq sharded
        over `axis_name`).  Requires q heads (and kv heads, unless they
        are broadcast up) to divide the sequence-axis size.
      mesh: the jax.sharding.Mesh to run under.
    """
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    sp = sp_common.sp_degree(mesh, axis_name)
    if sp <= 1 and (mesh is None or axis_name not in mesh.axis_names):
        # Degenerate slice without the axis: one party's all-to-all is
        # the identity, so this IS plain causal flash.
        return flash_attention(q, k, v, causal=causal,
                               sm_scale=float(sm_scale),
                               block_q=block_q, block_k=block_k)
    spec, _, tp = sp_common.sp_partition(mesh, axis_name)
    # Heads are sharded tensor-wise first, then each tensor shard's
    # heads are all-to-all'd over the sequence axis — so heads must
    # divide tp * sp.
    if q.shape[1] % (tp * sp):
        raise ValueError(
            f'ulysses needs num_heads ({q.shape[1]}) divisible by '
            f'tensor ({tp}) x {axis_name} ({sp}); use ring attention '
            'instead.')
    k, v = sp_common.broadcast_gqa_if_indivisible(q, k, v, tp * sp)
    fn = functools.partial(_ulysses_attention_sharded,
                           axis_name=axis_name, sm_scale=float(sm_scale),
                           causal=causal, block_q=block_q, block_k=block_k)
    return sp_common.sp_shard_map(fn, mesh, (spec, spec, spec),
                                  spec)(q, k, v)
