"""Shared shard_map plumbing for the sequence-parallel attention ops.

ring_attention and ulysses_attention wrap the same mesh logic: batch
stays on the data axes, heads on the tensor axis, only the sequence dim
participates in the SP collective.  One copy here so axis selection and
the GQA fallback cannot diverge between the two strategies.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax


def sp_partition(mesh, axis_name: str) -> Tuple[object, tuple, int]:
    """→ (PartitionSpec for [b, h, s, d], head_axes, tensor degree)."""
    P = jax.sharding.PartitionSpec

    def _axes(*names):
        present = tuple(a for a in names if a in mesh.axis_names and
                        mesh.shape[a] > 1)
        return present if present else None

    batch_axes = _axes('data', 'fsdp')
    head_axes = _axes('tensor')
    tp = 1
    for a in (head_axes or ()):
        tp *= mesh.shape[a]
    return P(batch_axes, head_axes, axis_name, None), head_axes, tp


def broadcast_gqa_if_indivisible(q, k, v, divisor: int):
    """Broadcast kv heads up to q heads when they don't divide the head
    sharding (`divisor` = the product of head-sharding mesh axes)."""
    if k.shape[1] % divisor:
        from skypilot_tpu.ops.attention import _repeat_kv  # pylint: disable=import-outside-toplevel
        k, v = _repeat_kv(q, k, v)
    return k, v
