"""Shared shard_map plumbing for the sequence-parallel attention ops.

ring_attention and ulysses_attention wrap the same mesh logic: batch
stays on the data axes, heads on the tensor axis, only the sequence dim
participates in the SP collective.  One copy here so axis selection and
the GQA fallback cannot diverge between the two strategies.

Degenerate meshes are first-class: a slice-serving replica builds ONE
mesh per slice and runs the SAME prefill code whether the slice has one
host or eight — so `sp_degree` treats a missing sequence axis (or one
of size 1) as degree 1, and the wrappers fall back to the plain flash
kernel there instead of spinning up a one-party collective.  This is
what lets `serve/slice_replica.py` ship a single code path for every
`num_hosts:` value.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax


def sp_shard_map(fn, mesh, in_specs, out_specs):
    """Version-portable shard_map (same capability split as
    parallel/preflight.py `_shard_map`): `jax.shard_map` is the public
    API from jax 0.6+ (replication checking via check_vma); older jax
    only ships `jax.experimental.shard_map.shard_map`, whose
    replication checker predates several collectives used here — so it
    runs with check_rep=False, exactly like the preflight probe."""
    if hasattr(jax, 'shard_map'):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental import shard_map as shard_map_lib  # pylint: disable=import-outside-toplevel
    return shard_map_lib.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_rep=False)


def sp_degree(mesh, axis_name: str) -> int:
    """Size of the sequence-parallel axis; 1 when the mesh does not
    carry the axis at all (degenerate single-host slice) or carries it
    at size 1 — both mean "no sequence collective", and callers must
    treat them identically."""
    if mesh is None or axis_name not in mesh.axis_names:
        return 1
    return int(mesh.shape[axis_name])


def sp_partition(mesh, axis_name: str) -> Tuple[object, tuple, int]:
    """→ (PartitionSpec for [b, h, s, d], head_axes, tensor degree).

    Accepts a degenerate mesh (sequence axis of size 1): the axis still
    appears in the spec — shard_map over a size-1 axis is exact, the
    ring simply has one hop — so the same jitted program serves every
    slice width.  A mesh MISSING the axis entirely is the caller's cue
    to skip shard_map (see `sp_degree`); putting an unknown axis in a
    PartitionSpec would be an error, so it is omitted here.
    """
    P = jax.sharding.PartitionSpec

    def _axes(*names):
        present = tuple(a for a in names if a in mesh.axis_names and
                        mesh.shape[a] > 1)
        return present if present else None

    batch_axes = _axes('data', 'fsdp')
    head_axes = _axes('tensor')
    tp = 1
    for a in (head_axes or ()):
        tp *= mesh.shape[a]
    seq_axis = axis_name if axis_name in mesh.axis_names else None
    return P(batch_axes, head_axes, seq_axis, None), head_axes, tp


def broadcast_gqa_if_indivisible(q, k, v, divisor: int):
    """Broadcast kv heads up to q heads when they don't divide the head
    sharding (`divisor` = the product of head-sharding mesh axes)."""
    if k.shape[1] % divisor:
        from skypilot_tpu.ops.attention import _repeat_kv  # pylint: disable=import-outside-toplevel
        k, v = _repeat_kv(q, k, v)
    return k, v
