"""Pallas paged-attention decode kernel: block-table reads in-kernel.

The paged engine's fallback decode path gathers every slot's pages
into a dense `[b, h_kv, len, d]` view before attending
(`paged_batched_step`'s view closure) — fine on CPU emulation, a
bandwidth disaster on TPU: the gather materialises the whole cache
window in HBM every tick.  This kernel reads K/V pages directly from
the page pool by block-table index inside the kernel grid — the
gathered view never exists.  Grid is (slot, kv_head, table_row); the
block tables and per-slot lengths ride in scalar-prefetch memory so
each program's K/V BlockSpec index map picks its pool page
dynamically, and an online softmax accumulates across the table-row
grid axis in VMEM scratch (TPU grids iterate the minor axis
sequentially, so scratch carries between pages of the same slot).

Queries generalise to S tokens per slot (query row r sits at absolute
position `lengths[b] + r % S`), so one kernel serves single-token
decode (S=1) AND the self-speculative verify step (S=k+1) — drafts
are verified through the same paged kernel.

int8 pools (PR 7's per-page absmax scales) use a separate kernel body
with fused dequant on the loaded K/V operand: the int8 bytes are what
moves from HBM, the multiply happens on the VMEM-resident block.

Same interpret-mode-on-CPU pattern as ops/attention.py
(`SKYTPU_PALLAS_INTERPRET=1`); off-TPU without interpret mode a pure
`jnp` gather reference with identical masking math is used, and
`SKYTPU_DECODE_KERNEL=pallas|gather` pins the engine's path choice
(default: pallas wherever Pallas can run, else gather).

Shapes: q [B, h_q, S, d]; pool leaves [n_pages, h_kv, ps, d] (int8
pools: {'q': int8, 'scale': f32 [n_pages, h_kv, ps]}); tables [B, P];
lengths [B] (pre-write depths — the S new tokens are assumed already
written at positions lengths..lengths+S-1, exactly how
`paged_batched_step` orders write-then-attend).
"""
from __future__ import annotations

import functools
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp

from skypilot_tpu.ops.attention import NEG_INF
from skypilot_tpu.ops.attention import _LANES
from skypilot_tpu.ops.attention import _interpret
from skypilot_tpu.ops.attention import _use_pallas

KERNEL_CHOICES = ('pallas', 'gather')


def decode_kernel_choice() -> str:
    """Resolve the decode attention path: 'pallas' (this kernel) or
    'gather' (the dense page-gather view).  SKYTPU_DECODE_KERNEL pins
    it; default is pallas wherever Pallas can run (TPU, or CPU with
    SKYTPU_PALLAS_INTERPRET=1) and gather otherwise."""
    choice = os.environ.get('SKYTPU_DECODE_KERNEL', '').strip().lower()
    if choice:
        if choice not in KERNEL_CHOICES:
            raise ValueError(
                f'SKYTPU_DECODE_KERNEL={choice!r}: expected one of '
                f'{KERNEL_CHOICES}')
        return choice
    return 'pallas' if _use_pallas() else 'gather'


def _dequant_block(vals, scale, dtype):
    """Fused per-token dequant of one loaded [ps, d] int8 block."""
    return vals.astype(dtype) * scale.astype(dtype)[:, None]


def _paged_kernel_body(i, q, k, v, length, acc_ref, m_ref, l_ref, *,
                       page_size: int, s_q: int):
    """Online-softmax update of one (slot, kv_head, table_row)
    program.  q [R, d] pre-scaled f32 (R = rep * s_q); k/v [ps, d]
    f32; `length` the slot's pre-write depth.  Scratch acc [R, d],
    m/l [R, _LANES] (per-row scalars broadcast across lanes for
    Mosaic tiling, like the flash kernels' LSE layout)."""
    r = q.shape[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    kpos = i * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (r, page_size), 1)
    # Query row r sits at absolute position length + (r % s_q): the
    # GQA fold keeps the S query tokens of each q-head contiguous.
    qpos = length + jax.lax.broadcasted_iota(
        jnp.int32, (r, page_size), 0) % s_q
    s = jnp.where(kpos <= qpos, s, NEG_INF)
    m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)
    l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = jnp.broadcast_to(m_new, (r, _LANES))
    l_ref[...] = jnp.broadcast_to(l_new, (r, _LANES))


def _paged_decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref,
                         o_ref, acc_ref, m_ref, l_ref, *,
                         page_size: int, s_q: int, num_rows: int):
    """Native-dtype pool kernel: one (slot, kv_head, table_row)
    program streams its pool page through VMEM."""
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel

    b = pl.program_id(0)
    i = pl.program_id(2)
    length = lengths_ref[b]

    @pl.when(i == 0)
    def _init():  # pylint: disable=unused-variable
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Pages past the written window contribute nothing; row 0 always
    # computes (kpos 0 <= length), so m is finite from the first page.
    @pl.when(i * page_size <= length + s_q - 1)
    def _compute():  # pylint: disable=unused-variable
        _paged_kernel_body(
            i, q_ref[0, 0].astype(jnp.float32),
            k_ref[0, 0].astype(jnp.float32),
            v_ref[0, 0].astype(jnp.float32), length,
            acc_ref, m_ref, l_ref, page_size=page_size, s_q=s_q)

    @pl.when(i == num_rows - 1)
    def _finish():  # pylint: disable=unused-variable
        l = jnp.max(l_ref[...], axis=-1, keepdims=True)
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _paged_decode_kernel_int8(tables_ref, lengths_ref, q_ref, k_ref,
                              ks_ref, v_ref, vs_ref, o_ref, acc_ref,
                              m_ref, l_ref, *, page_size: int, s_q: int,
                              num_rows: int):
    """int8 pool kernel: same program shape, with the per-page absmax
    scales fused into the loaded K/V blocks (dequant on the VMEM
    operand — int8 is what crossed HBM)."""
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel

    b = pl.program_id(0)
    i = pl.program_id(2)
    length = lengths_ref[b]

    @pl.when(i == 0)
    def _init():  # pylint: disable=unused-variable
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i * page_size <= length + s_q - 1)
    def _compute():  # pylint: disable=unused-variable
        k = _dequant_block(k_ref[0, 0], ks_ref[0, 0], jnp.float32)
        v = _dequant_block(v_ref[0, 0], vs_ref[0, 0], jnp.float32)
        _paged_kernel_body(
            i, q_ref[0, 0].astype(jnp.float32), k, v, length,
            acc_ref, m_ref, l_ref, page_size=page_size, s_q=s_q)

    @pl.when(i == num_rows - 1)
    def _finish():  # pylint: disable=unused-variable
        l = jnp.max(l_ref[...], axis=-1, keepdims=True)
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_leaf, v_leaf, tables, lengths, *,
                            sm_scale: float):
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel
    from jax.experimental.pallas import tpu as pltpu  # pylint: disable=import-outside-toplevel

    b, h_q, s_q, d = q.shape
    quantized = isinstance(k_leaf, dict)
    pool = k_leaf['q'] if quantized else k_leaf
    h_kv, ps = pool.shape[1], pool.shape[2]
    rep = h_q // h_kv
    r = rep * s_q
    num_rows = tables.shape[1]
    # Fold GQA + the S query tokens into one row axis: row
    # qh_local * s_q + j is q-head (qh_local within the kv group) at
    # query token j.  sm_scale is folded into q once, outside.
    qr = (q.reshape(b, h_kv, rep, s_q, d).reshape(b, h_kv, r, d)
          .astype(jnp.float32) * sm_scale)
    tables = jnp.asarray(tables, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)

    grid = (b, h_kv, num_rows)
    q_spec = pl.BlockSpec(
        (1, 1, r, d), lambda bb, hh, ii, tt, ll: (bb, hh, 0, 0),
        memory_space=pltpu.VMEM)
    # The block-table read happens HERE: each program's K/V page is
    # pool row tables[b, i] — the gathered view never materialises.
    kv_spec = pl.BlockSpec(
        (1, 1, ps, d),
        lambda bb, hh, ii, tt, ll: (tt[bb, ii], hh, 0, 0),
        memory_space=pltpu.VMEM)
    scale_spec = pl.BlockSpec(
        (1, 1, ps), lambda bb, hh, ii, tt, ll: (tt[bb, ii], hh, 0),
        memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec(
        (1, 1, r, d), lambda bb, hh, ii, tt, ll: (bb, hh, 0, 0),
        memory_space=pltpu.VMEM)
    scratch = [pltpu.VMEM((r, d), jnp.float32),
               pltpu.VMEM((r, _LANES), jnp.float32),
               pltpu.VMEM((r, _LANES), jnp.float32)]
    if quantized:
        kernel = functools.partial(
            _paged_decode_kernel_int8, page_size=ps, s_q=s_q,
            num_rows=num_rows)
        in_specs = [q_spec, kv_spec, scale_spec, kv_spec, scale_spec]
        operands = (qr, k_leaf['q'], k_leaf['scale'], v_leaf['q'],
                    v_leaf['scale'])
    else:
        kernel = functools.partial(
            _paged_decode_kernel, page_size=ps, s_q=s_q,
            num_rows=num_rows)
        in_specs = [q_spec, kv_spec, kv_spec]
        operands = (qr, k_leaf, v_leaf)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=scratch),
        out_shape=jax.ShapeDtypeStruct((b, h_kv, r, d), q.dtype),
        interpret=_interpret(),
    )(tables, lengths, *operands)
    return out.reshape(b, h_kv, rep, s_q, d).reshape(b, h_q, s_q, d)


def _paged_attention_reference(q, k_leaf, v_leaf, tables, lengths, *,
                               sm_scale: float):
    """Pure-jnp reference with the kernel's exact masking math: gather
    the pool rows each table names, dequant, attend.  Used off-TPU
    without interpret mode (and by parity tests as the pinned
    semantics of the kernel)."""
    b, h_q, s_q, d = q.shape
    quantized = isinstance(k_leaf, dict)

    def gather(leaf):
        if quantized:
            vals = leaf['q'][tables].astype(jnp.float32)
            scale = leaf['scale'][tables].astype(jnp.float32)
            arr = vals * scale[..., None]
        else:
            arr = leaf[tables].astype(jnp.float32)
        bb, p, h, s, dd = arr.shape
        return arr.transpose(0, 2, 1, 3, 4).reshape(bb, h, p * s, dd)

    k = gather(k_leaf)                              # [B, h_kv, P*ps, d]
    v = gather(v_leaf)
    h_kv = k.shape[1]
    rep = h_q // h_kv
    qg = q.reshape(b, h_kv, rep, s_q, d).astype(jnp.float32)
    s = jnp.einsum('bgrqd,bgkd->bgrqk', qg, k) * sm_scale
    kpos = jnp.arange(k.shape[2])
    qpos = lengths[:, None] + jnp.arange(s_q)[None, :]      # [B, S]
    mask = (kpos[None, None, None, None, :] <=
            qpos[:, None, None, :, None])
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bgrqk,bgkd->bgrqd', p, v)
    return out.reshape(b, h_q, s_q, d).astype(q.dtype)


def paged_attention(q, k_leaf: Any, v_leaf: Any, tables, lengths, *,
                    sm_scale: Optional[float] = None):
    """Paged decode attention over one layer's page pool.

    q [B, h_q, S, d] (query token j of slot b at absolute position
    lengths[b] + j, already written into the pool); pool leaves
    [n_pages, h_kv, ps, d] (or int8 {'q','scale'}); tables [B, P];
    lengths [B].  Returns [B, h_q, S, d] in q's dtype.
    """
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if _use_pallas():
        return _paged_attention_pallas(q, k_leaf, v_leaf, tables,
                                       lengths, sm_scale=sm_scale)
    return _paged_attention_reference(q, k_leaf, v_leaf, tables,
                                      lengths, sm_scale=sm_scale)
