"""TPU compute kernels (Pallas) with pure-JAX fallbacks.

The reference framework ships zero kernels (SURVEY.md §2.1 — no native
code); long-context and model compute are delegated entirely to user
payloads.  In this framework they are first-class: flash attention on a
single chip, ring attention across the 'sequence' mesh axis for
long-context (SURVEY.md §5), both differentiable.
"""
from skypilot_tpu.ops.attention import flash_attention
from skypilot_tpu.ops.attention import flash_attention_with_lse
from skypilot_tpu.ops.ring_attention import ring_attention
from skypilot_tpu.ops.ulysses_attention import ulysses_attention

__all__ = ['flash_attention', 'flash_attention_with_lse', 'ring_attention',
           'ulysses_attention']
