"""Ring attention: sequence-parallel causal attention over the ICI ring.

Long-context design (SURVEY.md §5 — absent in the reference): the
sequence is sharded over the 'sequence' mesh axis; each device holds its
local q/k/v chunk and, for `ring_size` steps, attends its q against the
currently-resident k/v chunk with online-softmax accumulation while
`ppermute`-ing the k/v chunks one hop around the ring.  Compute and
ICI transfer overlap (XLA schedules the ppermute DMA alongside the
attention matmuls), so the hot loop stays MXU-bound.

Differentiable: autodiff through the ring (ppermute transposes to the
reverse permutation) reproduces the blockwise backward.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from skypilot_tpu.ops.attention import NEG_INF


def _ring_step_attend(q, k, v, q_chunk_idx, kv_chunk_idx, chunk_len,
                      sm_scale, causal):
    """Attend local q [b,h,s,d] against one k/v chunk; returns (o,m,l)
    partials in float32."""
    s = jnp.einsum('bhqd,bhkd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        qpos = q_chunk_idx * chunk_len + jnp.arange(chunk_len)
        kpos = kv_chunk_idx * chunk_len + jnp.arange(chunk_len)
        mask = kpos[None, :] <= qpos[:, None]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum('bhqk,bhkd->bhqd', p, v.astype(jnp.float32))
    return o, m, l


def _ring_attention_sharded(q, k, v, *, axis_name: str, sm_scale: float,
                            causal: bool):
    """Body run under shard_map: q/k/v are per-device chunks."""
    ring_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    chunk_len = q.shape[2]
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    @jax.checkpoint
    def step(carry, step_idx):
        o, m, l, k_cur, v_cur = carry
        # k/v chunk currently resident came from device (my_idx - step).
        kv_idx = (my_idx - step_idx) % ring_size
        o_p, m_p, l_p = _ring_step_attend(q, k_cur, v_cur, my_idx, kv_idx,
                                          chunk_len, sm_scale, causal)
        m_new = jnp.maximum(m, m_p)
        corr = jnp.exp(m - m_new)
        corr_p = jnp.exp(m_p - m_new)
        l_new = l * corr + l_p * corr_p
        o_new = o * corr[..., None] + o_p * corr_p[..., None]
        # Rotate k/v one hop around the ring (skipped result unused on
        # the last step; XLA overlaps this DMA with the matmuls above).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    b, h, s, d = q.shape
    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    (o, _, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(ring_size))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, *, mesh, axis_name: str = 'sequence',
                   causal: bool = True, sm_scale: Optional[float] = None):
    """Sequence-parallel attention.

    Args:
      q, k, v: [batch, heads, seq, head_dim] GLOBAL arrays (seq sharded
        over `axis_name`).
      mesh: the jax.sharding.Mesh to run under.
    """
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    from jax.experimental.shard_map import shard_map  # pylint: disable=import-outside-toplevel
    P = jax.sharding.PartitionSpec

    # Keep batch on the data axes and heads on the tensor axis — only
    # the sequence dim participates in the ring.  Replicating them here
    # would force all-gathers and redundant compute across every
    # non-sequence mesh axis.
    def _axes(*names):
        present = tuple(a for a in names if a in mesh.axis_names and
                        mesh.shape[a] > 1)
        return present if present else None

    batch_axes = _axes('data', 'fsdp')
    head_axes = _axes('tensor')
    spec = P(batch_axes, head_axes, axis_name, None)
    fn = functools.partial(_ring_attention_sharded, axis_name=axis_name,
                           sm_scale=float(sm_scale), causal=causal)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)
