"""Ring attention: sequence-parallel causal attention over the ICI ring.

Long-context design (SURVEY.md §5 — absent in the reference): the
sequence is sharded over the 'sequence' mesh axis; each device holds its
local q/k/v chunk and, for `ring_size` steps, attends its q against the
currently-resident k/v chunk while `ppermute`-ing the k/v chunks one hop
around the ring.  Compute and ICI transfer overlap (XLA schedules the
ppermute DMA alongside the attention matmuls), so the hot loop stays
MXU-bound.

Each hop's attend is the FLASH KERNEL (ops/attention.py — Pallas on
TPU), not a full-chunk einsum: the kernel returns (out, lse) and hops
combine with a logaddexp-weighted merge.  Chunk-level causality is
decided per hop with `lax.switch`: diagonal chunk -> causal flash,
earlier chunk -> full flash, later chunk -> skipped (zero contribution),
so ~half the hops do no attention FLOPs at all.

Differentiable: autodiff through the ring (ppermute transposes to the
reverse permutation); the flash op propagates both out- and
lse-cotangents into its Pallas backward.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from skypilot_tpu.ops import sp_common
from skypilot_tpu.ops.attention import NEG_INF
from skypilot_tpu.ops.attention import flash_attention_with_lse


def _ring_attention_sharded(q, k, v, *, axis_name: str, sm_scale: float,
                            causal: bool, block_q: int, block_k: int):
    """Body run under shard_map: q/k/v are per-device chunks."""
    ring_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
    b, h, s, d = q.shape

    def attend(is_causal):
        def fn(args):
            k_cur, v_cur = args
            out, lse = flash_attention_with_lse(
                q, k_cur, v_cur, causal=is_causal, sm_scale=sm_scale,
                block_q=block_q, block_k=block_k)
            return out.astype(jnp.float32), lse
        return fn

    def skip(args):
        del args
        return (jnp.zeros((b, h, s, d), jnp.float32),
                jnp.full((b, h, s), NEG_INF, jnp.float32))

    @jax.checkpoint
    def step(carry, step_idx):
        o, lse, k_cur, v_cur = carry
        # k/v chunk currently resident came from device (my_idx - step).
        kv_idx = (my_idx - step_idx) % ring_size
        if causal:
            # 0: diagonal (causal flash), 1: earlier chunk (full flash),
            # 2: later chunk (skip — fully masked).
            branch = jnp.where(kv_idx == my_idx, 0,
                               jnp.where(kv_idx < my_idx, 1, 2))
            o_c, lse_c = jax.lax.switch(
                branch, [attend(True), attend(False), skip],
                (k_cur, v_cur))
        else:
            o_c, lse_c = attend(False)((k_cur, v_cur))
        # Online-softmax merge of normalized partials.  NEG_INF is a
        # finite sentinel, so exp(lse - lse_new) stays NaN-free even for
        # fully-masked rows.
        lse_new = jnp.logaddexp(lse, lse_c)
        alpha = jnp.exp(lse - lse_new)
        beta = jnp.exp(lse_c - lse_new)
        o_new = o * alpha[..., None] + o_c * beta[..., None]
        # Rotate k/v one hop around the ring (skipped result unused on
        # the last step; XLA overlaps this DMA with the matmuls above).
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, lse_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    lse0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
    (o, _, _, _), _ = jax.lax.scan(
        step, (o0, lse0, k, v), jnp.arange(ring_size))
    return o.astype(q.dtype)


def ring_attention(q, k, v, *, mesh, axis_name: str = 'sequence',
                   causal: bool = True, sm_scale: Optional[float] = None,
                   block_q: int = 128, block_k: int = 128):
    """Sequence-parallel attention.

    Args:
      q, k, v: [batch, heads, seq, head_dim] GLOBAL arrays (seq sharded
        over `axis_name`).
      mesh: the jax.sharding.Mesh to run under.
    """
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if sp_common.sp_degree(mesh, axis_name) <= 1 and (
            mesh is None or axis_name not in mesh.axis_names):
        # Degenerate slice without the axis at all: a one-hop ring IS
        # the plain causal flash kernel — run it directly rather than
        # reference an axis the mesh does not carry.
        out, _ = flash_attention_with_lse(
            q, k, v, causal=causal, sm_scale=float(sm_scale),
            block_q=block_q, block_k=block_k)
        return out
    # Keep batch on the data axes and heads on the tensor axis — only
    # the sequence dim participates in the ring.  Replicating them here
    # would force all-gathers and redundant compute across every
    # non-sequence mesh axis.  (Shared with ulysses: ops/sp_common.py.)
    spec, head_axes, tp = sp_common.sp_partition(mesh, axis_name)
    if head_axes:
        # GQA kv heads must divide the tensor axis or be broadcast up
        # to q heads (the Pallas kernel's index-map GQA still applies
        # within the shard when kv heads DO divide).
        k, v = sp_common.broadcast_gqa_if_indivisible(q, k, v, tp)
    fn = functools.partial(_ring_attention_sharded, axis_name=axis_name,
                           sm_scale=float(sm_scale), causal=causal,
                           block_q=block_q, block_k=block_k)
    return sp_common.sp_shard_map(fn, mesh, (spec, spec, spec),
                                  spec)(q, k, v)
