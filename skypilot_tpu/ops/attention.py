"""Causal multi-head attention: Pallas flash kernels + blockwise fallback.

Design (TPU-first):
- Forward on TPU uses a Pallas flash-attention kernel: online softmax,
  q-blocks on the grid, k-blocks streamed through VMEM, matmuls in
  bfloat16 onto the MXU with float32 accumulation.  The kernel also
  emits the per-row logsumexp (LSE).
- Backward on TPU is two Pallas kernels (recompute-style flash
  backward): a dq kernel gridded over q-blocks and a fused dk/dv kernel
  gridded over k-blocks, both recomputing p = exp(s - lse) instead of
  materialising the O(seq^2) probability matrix, with causal
  block-skipping.  `delta = rowsum(dO * O)` is a cheap XLA-fused
  pre-pass.
- On CPU (tests) the same kernels run under Pallas interpret mode when
  SKYTPU_PALLAS_INTERPRET=1; otherwise a blockwise `lax.scan`
  implementation with identical online-softmax math is used, and its
  autodiff is the backward.

No reference equivalent: SkyPilot ships no kernels (SURVEY.md §2.1).
Shapes follow [batch, num_heads, seq, head_dim].
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30
# Padded q rows get LSE=+BIG so recomputed p = exp(s - lse) underflows
# to exactly 0 in the backward kernels (no separate validity mask).
LSE_PAD = 1e30
# Mosaic requires the last two dims of every block to be divisible by
# (8, 128) (f32 tile) or equal to the array dims.  Per-row scalars (LSE,
# delta) therefore ride in a broadcast 128-lane trailing dim — the same
# layout the official JAX TPU flash kernel uses for its l/m residuals.
_LANES = 128


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == 'tpu'
    except Exception:  # pylint: disable=broad-except
        return False


def _interpret() -> bool:
    """Run the Pallas kernels in interpret mode (CPU tests)."""
    return os.environ.get('SKYTPU_PALLAS_INTERPRET', '') == '1'


def _use_pallas() -> bool:
    return _on_tpu() or _interpret()


def _repeat_kv(q, k, v):
    """GQA: broadcast kv heads up to q heads (XLA paths only — the
    Pallas kernels instead fold the repeat into their index maps so the
    repeated K/V never materialises in HBM)."""
    rep = q.shape[1] // k.shape[1]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return k, v


def mha_reference(q, k, v, *, causal: bool = True,
                  sm_scale: Optional[float] = None):
    """O(seq^2)-memory reference attention (tests / tiny shapes)."""
    k, v = _repeat_kv(q, k, v)
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        qpos = jnp.arange(q_len)[:, None] + (k_len - q_len)
        kpos = jnp.arange(k_len)[None, :]
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum('bhqk,bhkd->bhqd', probs, v).astype(q.dtype)


def _blockwise_attention(q, k, v, *, causal: bool, sm_scale: float,
                         block_k: int, return_lse: bool = False):
    """Online-softmax attention scanning over k/v blocks."""
    k, v = _repeat_kv(q, k, v)
    orig_dtype = q.dtype
    b, h, q_len, d = q.shape
    k_len = k.shape[2]
    num_blocks = max(1, (k_len + block_k - 1) // block_k)
    pad = num_blocks * block_k - k_len
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, num_blocks, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, num_blocks, block_k, d).transpose(2, 0, 1, 3, 4)

    q32 = q.astype(jnp.float32)
    qpos = jnp.arange(q_len) + (k_len - q_len)

    @jax.checkpoint
    def step(carry, blk):
        o, m, l = carry
        k_blk, v_blk, blk_idx = blk
        s = jnp.einsum('bhqd,bhkd->bhqk', q32, k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * sm_scale
        kpos = blk_idx * block_k + jnp.arange(block_k)
        mask = kpos[None, :] < k_len  # padding mask
        if causal:
            mask = mask & (kpos[None, :] <= qpos[:, None])
        s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            'bhqk,bhkd->bhqd', p, v_blk.astype(jnp.float32))
        return (o_new, m_new, l_new), None

    o0 = jnp.zeros((b, h, q_len, d), jnp.float32)
    m0 = jnp.full((b, h, q_len), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, q_len), jnp.float32)
    (o, m, l), _ = jax.lax.scan(
        step, (o0, m0, l0),
        (kb, vb, jnp.arange(num_blocks)))
    out = (o / jnp.maximum(l, 1e-30)[..., None]).astype(orig_dtype)
    if return_lse:
        return out, m + jnp.log(jnp.maximum(l, 1e-30))
    return out


# ---------------------------------------------------------------- Pallas


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      sm_scale: float, causal: bool, block_k: int,
                      k_len: int, pos_offset: int):
    """One (batch*head, q_block) program: stream k/v blocks through VMEM.

    Refs: q [1, block_q, d]; k/v [1, k_len_padded, d]; o [1, block_q, d];
    lse [1, block_q, _LANES] (per-row LSE broadcast across the lane dim
    so the block satisfies Mosaic tiling).  Leading dim is the
    batch*head grid axis, blocked to 1.  Row-wise softmax stats are kept
    as 2D (block_q, 1) values for layout-safe Mosaic lowering.
    """
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel

    _, block_q, d = q_ref.shape
    q_blk_idx = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale
    # pos_offset = k_len - q_len aligns the causal diagonal when q is a
    # suffix of the kv sequence (decode-style q_len < k_len), matching
    # mha_reference/_blockwise_attention.
    qpos = pos_offset + q_blk_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    num_k_blocks = pl.cdiv(k_len, block_k)
    if causal:
        # Skip k-blocks strictly above the diagonal for this q-block.
        num_k_blocks = jnp.minimum(
            num_k_blocks,
            pl.cdiv(pos_offset + (q_blk_idx + 1) * block_q, block_k))

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kpos < k_len
        if causal:
            mask &= kpos <= qpos
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, num_k_blocks, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l_safe), (block_q, _LANES))


def _flash_fwd_pallas(q, k, v, *, causal: bool, sm_scale: float,
                      block_q: int, block_k: int):
    """Returns (out [b,h,q,d], lse [b,h,q] float32)."""
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel
    from jax.experimental.pallas import tpu as pltpu  # pylint: disable=import-outside-toplevel

    b, h, q_len, d = q.shape
    h_kv, k_len = k.shape[1], k.shape[2]
    # GQA: the kernel maps q-head bh to kv-head bh // rep via the k/v
    # index maps — the repeated K/V never exists in HBM.
    rep = h // h_kv
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    # Pad seq lens to block multiples; kernel masks the padding.
    q_pad = (-q_len) % block_q
    k_pad = (-k_len) % block_k
    if q_pad:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, q_pad), (0, 0)))
    if k_pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, k_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, k_pad), (0, 0)))
    qp = q.reshape(b * h, q_len + q_pad, d)
    kp = k.reshape(b * h_kv, k_len + k_pad, d)
    vp = v.reshape(b * h_kv, k_len + k_pad, d)

    grid = (b * h, (q_len + q_pad) // block_q)
    kernel = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_k=block_k, k_len=k_len,
                               pos_offset=k_len - q_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_len + k_pad, d),
                         lambda bh, qi: (bh // rep, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_len + k_pad, d),
                         lambda bh, qi: (bh // rep, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, _LANES), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, q_len + q_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, q_len + q_pad, _LANES),
                                 jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    return (out.reshape(b, h, q_len + q_pad, d)[:, :, :q_len],
            lse[:, :, 0].reshape(b, h, q_len + q_pad)[:, :, :q_len])


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, sm_scale: float, causal: bool,
                         block_k: int, k_len: int, pos_offset: int):
    """dQ for one (batch*head, q_block): stream k/v blocks, recompute
    p = exp(s - lse).  dS = P * (dP - delta); dQ = scale * dS @ K."""
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel

    _, block_q, d = q_ref.shape
    q_blk_idx = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    # lse/delta blocks are [1, block_q, _LANES] with all lanes equal; a
    # lane-max recovers the per-row scalar as a 2D (block_q, 1) value.
    lse = jnp.max(lse_ref[0], axis=-1, keepdims=True)
    delta = jnp.max(delta_ref[0], axis=-1, keepdims=True)
    qpos = pos_offset + q_blk_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    num_k_blocks = pl.cdiv(k_len, block_k)
    if causal:
        num_k_blocks = jnp.minimum(
            num_k_blocks,
            pl.cdiv(pos_offset + (q_blk_idx + 1) * block_q, block_k))

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kpos < k_len
        if causal:
            mask &= kpos <= qpos
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_k_blocks, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                          block_q: int, q_len: int, pos_offset: int):
    """Fused dK/dV for one (batch*head, k_block): stream q/do blocks.
    dV = P^T @ dO; dK = scale * dS^T @ Q.  Padded q rows carry
    lse=LSE_PAD so their recomputed p underflows to 0."""
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel

    _, block_k, d = k_ref.shape
    k_blk_idx = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    kpos = k_blk_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    num_q_blocks = pl.cdiv(q_len, block_q)
    if causal:
        # First q block whose last row can see this k block:
        # qpos >= kpos  <=>  qi >= kpos - pos_offset.
        first = jnp.maximum(
            0, (k_blk_idx * block_k - pos_offset) // block_q)
    else:
        first = 0

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(
            jnp.float32)
        lse_blk = jnp.max(lse_ref[0, pl.ds(qb * block_q, block_q), :],
                          axis=-1, keepdims=True)
        delta_blk = jnp.max(delta_ref[0, pl.ds(qb * block_q, block_q), :],
                            axis=-1, keepdims=True)
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        qpos = pos_offset + qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = kpos >= 0  # k padding handled by caller slicing
        if causal:
            mask &= kpos <= qpos
        p = jnp.where(mask, jnp.exp(s - lse_blk), 0.0)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first, num_q_blocks, body, (dk0, dv0))
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, g_lse, *, causal: bool,
                      sm_scale: float, block_q: int, block_k: int):
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel
    from jax.experimental.pallas import tpu as pltpu  # pylint: disable=import-outside-toplevel

    b, h, q_len, d = q.shape
    h_kv, k_len = k.shape[1], k.shape[2]
    rep = h // h_kv
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    q_pad = (-q_len) % block_q
    k_pad = (-k_len) % block_k
    pos_offset = k_len - q_len

    # delta = rowsum(dO * O) — cheap XLA-fused pre-pass.  An incoming
    # LSE cotangent folds in exactly here: dS = P*(dP - delta + g_lse)
    # since dlse/dS = P, so delta_eff = delta - g_lse.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    if q_pad:
        pad4 = ((0, 0), (0, 0), (0, q_pad), (0, 0))
        q = jnp.pad(q, pad4)
        g = jnp.pad(g, pad4)
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, q_pad)),
                      constant_values=LSE_PAD)
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, q_pad)))
    if k_pad:
        pad4 = ((0, 0), (0, 0), (0, k_pad), (0, 0))
        k = jnp.pad(k, pad4)
        v = jnp.pad(v, pad4)
    qlp, klp = q_len + q_pad, k_len + k_pad
    qp = q.reshape(b * h, qlp, d)
    kp = k.reshape(b * h_kv, klp, d)
    vp = v.reshape(b * h_kv, klp, d)
    dop = g.reshape(b * h, qlp, d)
    # Per-row scalars ride in a broadcast 128-lane trailing dim so their
    # BlockSpecs satisfy Mosaic tiling (see _LANES).
    lsep = jnp.broadcast_to(lse.reshape(b * h, qlp)[:, :, None],
                            (b * h, qlp, _LANES))
    deltap = jnp.broadcast_to(delta.reshape(b * h, qlp)[:, :, None],
                              (b * h, qlp, _LANES))

    qd_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                           memory_space=pltpu.VMEM)
    q1_spec = pl.BlockSpec((1, block_q, _LANES),
                           lambda bh, qi: (bh, qi, 0),
                           memory_space=pltpu.VMEM)
    kfull_spec = pl.BlockSpec((1, klp, d), lambda bh, qi: (bh // rep, 0, 0),
                              memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_k=block_k, k_len=k_len,
                          pos_offset=pos_offset),
        grid=(b * h, qlp // block_q),
        in_specs=[qd_spec, kfull_spec, kfull_spec, qd_spec, q1_spec,
                  q1_spec],
        out_specs=qd_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, qlp, d), q.dtype),
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    kd_in_spec = pl.BlockSpec((1, block_k, d),
                              lambda bh, ki: (bh // rep, ki, 0),
                              memory_space=pltpu.VMEM)
    kd_out_spec = pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0),
                               memory_space=pltpu.VMEM)
    qfull_spec = pl.BlockSpec((1, qlp, d), lambda bh, ki: (bh, 0, 0),
                              memory_space=pltpu.VMEM)
    qfull1_spec = pl.BlockSpec((1, qlp, _LANES), lambda bh, ki: (bh, 0, 0),
                               memory_space=pltpu.VMEM)
    # GQA: each program computes q-head bh's contribution to kv-head
    # bh // rep; the per-q-head partials are group-summed below (one
    # cheap XLA reduction — dq/dk/dv stay a single kernel pass each).
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q, q_len=q_len,
                          pos_offset=pos_offset),
        grid=(b * h, klp // block_k),
        in_specs=[qfull_spec, kd_in_spec, kd_in_spec, qfull_spec,
                  qfull1_spec, qfull1_spec],
        out_specs=[kd_out_spec, kd_out_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, klp, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, klp, d), v.dtype)],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lsep, deltap)

    dq = dq.reshape(b, h, qlp, d)[:, :, :q_len]
    dk = dk.reshape(b, h_kv, rep, klp, d)[:, :, :, :k_len]
    dv = dv.reshape(b, h_kv, rep, klp, d)[:, :, :, :k_len]
    if rep > 1:
        # Sum in f32: rep-way bf16 accumulation would lose mantissa bits.
        dk = dk.astype(jnp.float32).sum(axis=2).astype(k.dtype)
        dv = dv.astype(jnp.float32).sum(axis=2).astype(v.dtype)
    else:
        dk = dk[:, :, 0]
        dv = dv[:, :, 0]
    return dq, dk, dv


# ------------------------------------------------------------- public op


def _flash_impl(q, k, v, causal, sm_scale, block_q, block_k):
    """Returns (out, lse)."""
    if _use_pallas():
        return _flash_fwd_pallas(q, k, v, causal=causal, sm_scale=sm_scale,
                                 block_q=block_q, block_k=block_k)
    return _blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                block_k=block_k, return_lse=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_impl(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_lse_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_impl(q, k, v, causal, sm_scale, block_q, block_k)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    if _use_pallas():
        # Kernel-grade backward: recompute-style Pallas dq + dk/dv.
        return _flash_bwd_pallas(q, k, v, out, lse, g_out, g_lse,
                                 causal=causal, sm_scale=sm_scale,
                                 block_q=block_q, block_k=block_k)
    # CPU fallback: autodiff of the blockwise forward (same math).
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_attention(
            q_, k_, v_, causal=causal, sm_scale=sm_scale, block_k=block_k,
            return_lse=True),
        q, k, v)
    return vjp((g_out, g_lse))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128):
    """Flash attention over [batch, heads, seq, head_dim] arrays."""
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    out, _ = _flash_lse(q, k, v, causal, float(sm_scale), block_q, block_k)
    return out


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             sm_scale: Optional[float] = None,
                             block_q: int = 128, block_k: int = 128):
    """Flash attention returning (out, lse) — the building block for
    ring attention's per-hop online-softmax combine.  Gradients flow
    through BOTH outputs (the LSE cotangent folds into the Pallas
    backward's delta term)."""
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    return _flash_lse(q, k, v, causal, float(sm_scale), block_q, block_k)
