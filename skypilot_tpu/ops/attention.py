"""Causal multi-head attention: Pallas flash kernel + blockwise fallback.

Design (TPU-first):
- Forward on TPU uses a Pallas flash-attention kernel: online softmax,
  q-blocks on the grid, k-blocks streamed through VMEM, matmuls in
  bfloat16 onto the MXU with float32 accumulation.
- Everywhere else (CPU tests, and the backward pass) uses a blockwise
  `lax.scan` implementation with the same online-softmax math — memory
  O(seq * block) instead of O(seq^2), so XLA can pipeline it, and
  autodiff through it is the flash backward recipe.

No reference equivalent: SkyPilot ships no kernels (SURVEY.md §2.1).
Shapes follow [batch, num_heads, seq, head_dim].
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == 'tpu'
    except Exception:  # pylint: disable=broad-except
        return False


def mha_reference(q, k, v, *, causal: bool = True,
                  sm_scale: Optional[float] = None):
    """O(seq^2)-memory reference attention (tests / tiny shapes)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        qpos = jnp.arange(q_len)[:, None] + (k_len - q_len)
        kpos = jnp.arange(k_len)[None, :]
        logits = jnp.where(kpos <= qpos, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum('bhqk,bhkd->bhqd', probs, v).astype(q.dtype)


def _blockwise_attention(q, k, v, *, causal: bool, sm_scale: float,
                         block_k: int):
    """Online-softmax attention scanning over k/v blocks."""
    orig_dtype = q.dtype
    b, h, q_len, d = q.shape
    k_len = k.shape[2]
    num_blocks = max(1, (k_len + block_k - 1) // block_k)
    pad = num_blocks * block_k - k_len
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, num_blocks, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, num_blocks, block_k, d).transpose(2, 0, 1, 3, 4)

    q32 = q.astype(jnp.float32)
    qpos = jnp.arange(q_len) + (k_len - q_len)

    @jax.checkpoint
    def step(carry, blk):
        o, m, l = carry
        k_blk, v_blk, blk_idx = blk
        s = jnp.einsum('bhqd,bhkd->bhqk', q32, k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * sm_scale
        kpos = blk_idx * block_k + jnp.arange(block_k)
        mask = kpos[None, :] < k_len  # padding mask
        if causal:
            mask = mask & (kpos[None, :] <= qpos[:, None])
        s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            'bhqk,bhkd->bhqd', p, v_blk.astype(jnp.float32))
        return (o_new, m_new, l_new), None

    o0 = jnp.zeros((b, h, q_len, d), jnp.float32)
    m0 = jnp.full((b, h, q_len), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, q_len), jnp.float32)
    (o, _, l), _ = jax.lax.scan(
        step, (o0, m0, l0),
        (kb, vb, jnp.arange(num_blocks)))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(orig_dtype)


# ---------------------------------------------------------------- Pallas


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale: float,
                      causal: bool, block_k: int, k_len: int,
                      pos_offset: int):
    """One (batch*head, q_block) program: stream k/v blocks through VMEM.

    Refs: q [1, block_q, d]; k/v [1, k_len_padded, d]; o [1, block_q, d]
    (leading dim is the batch*head grid axis, blocked to 1).
    """
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel

    _, block_q, d = q_ref.shape
    q_blk_idx = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale
    # pos_offset = k_len - q_len aligns the causal diagonal when q is a
    # suffix of the kv sequence (decode-style q_len < k_len), matching
    # mha_reference/_blockwise_attention.
    qpos = pos_offset + q_blk_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    num_k_blocks = pl.cdiv(k_len, block_k)
    if causal:
        # Skip k-blocks strictly above the diagonal for this q-block.
        num_k_blocks = jnp.minimum(
            num_k_blocks,
            pl.cdiv(pos_offset + (q_blk_idx + 1) * block_q, block_k))

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = kpos < k_len
        if causal:
            mask &= kpos <= qpos
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o, _, l = jax.lax.fori_loop(0, num_k_blocks, body, (o0, m0, l0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, *, causal: bool, sm_scale: float,
                      block_q: int, block_k: int):
    from jax.experimental import pallas as pl  # pylint: disable=import-outside-toplevel
    from jax.experimental.pallas import tpu as pltpu  # pylint: disable=import-outside-toplevel

    b, h, q_len, d = q.shape
    k_len = k.shape[2]
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    # Pad seq lens to block multiples; kernel masks the padding.
    q_pad = (-q_len) % block_q
    k_pad = (-k_len) % block_k
    if q_pad:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, q_pad), (0, 0)))
    if k_pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, k_pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, k_pad), (0, 0)))
    qp = q.reshape(b * h, q_len + q_pad, d)
    kp = k.reshape(b * h, k_len + k_pad, d)
    vp = v.reshape(b * h, k_len + k_pad, d)

    grid = (b * h, (q_len + q_pad) // block_q)
    kernel = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_k=block_k, k_len=k_len,
                               pos_offset=k_len - q_len)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_len + k_pad, d), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_len + k_pad, d), lambda bh, qi: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, q_len + q_pad, d), q.dtype),
    )(qp, kp, vp)
    return out.reshape(b, h, q_len + q_pad, d)[:, :, :q_len]


# ------------------------------------------------------------- public op


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    if _on_tpu():
        return _flash_fwd_pallas(q, k, v, causal=causal, sm_scale=sm_scale,
                                 block_q=block_q, block_k=block_k)
    return _blockwise_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                                block_k=block_k)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out = _flash(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    # Backward = autodiff of the blockwise forward (recompute; flash
    # backward recipe).  Same math as the Pallas forward.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_attention(
            q_, k_, v_, causal=causal, sm_scale=sm_scale, block_k=block_k),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128):
    """Flash attention over [batch, heads, seq, head_dim] arrays."""
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    return _flash(q, k, v, causal, float(sm_scale), block_q, block_k)
