"""Skylet periodic events: job scheduling, reconciliation, autostop.

Parity: /root/reference/sky/skylet/events.py:26-291 (SkyletEvent base with
per-event intervals; JobSchedulerEvent; AutostopEvent). The AutostopEvent
here stops/terminates the slice through the provision API using the provider
recorded in the autostop config — no Ray-YAML re-parsing and no monkey-
patched `ray up` (reference events.py:90-291).
"""
from __future__ import annotations

import time
import traceback

from skypilot_tpu import sky_logging
from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.skylet import job_lib

logger = sky_logging.init_logger(__name__)


class SkyletEvent:
    """Base: `run()` is invoked every EVENT_INTERVAL_SECONDS ticks."""
    EVENT_INTERVAL_SECONDS = 300

    def __init__(self) -> None:
        self._last_run_at = 0.0

    def maybe_run(self) -> None:
        now = time.time()
        if now - self._last_run_at < self.EVENT_INTERVAL_SECONDS:
            return
        self._last_run_at = now
        try:
            self.run()
        except Exception:  # pylint: disable=broad-except
            logger.error(f'{type(self).__name__} failed:\n'
                         f'{traceback.format_exc()}')

    def run(self) -> None:
        raise NotImplementedError


class JobSchedulerEvent(SkyletEvent):
    """Launch queued jobs FIFO + reconcile drifted statuses."""
    EVENT_INTERVAL_SECONDS = 20

    def run(self) -> None:
        job_lib.update_job_status()
        job_lib.scheduler.schedule_step()
        if not job_lib.is_cluster_idle():
            autostop_lib.set_last_active_time_to_now()


class AutostopEvent(SkyletEvent):
    """Stop/terminate this cluster after the configured idle window."""
    EVENT_INTERVAL_SECONDS = 60

    def run(self) -> None:
        config = autostop_lib.get_autostop_config()
        if config is None or not config.enabled:
            return
        if not job_lib.is_cluster_idle():
            return
        last_active = autostop_lib.get_last_active_time()
        idle_seconds = time.time() - last_active if last_active > 0 else 0.0
        if idle_seconds < config.autostop_idle_minutes * 60:
            return
        logger.info(
            f'Autostop: idle {idle_seconds / 60:.1f}m >= '
            f'{config.autostop_idle_minutes}m; '
            f'{"terminating" if config.down else "stopping"} '
            f'{config.cluster_name}.')
        from skypilot_tpu.provision import provisioner  # pylint: disable=import-outside-toplevel
        provisioner.teardown_cluster(config.provider_name,
                                     config.cluster_name,
                                     terminate=config.down)
