"""Skylet periodic events: job scheduling, reconciliation, autostop.

Parity: /root/reference/sky/skylet/events.py:26-291 (SkyletEvent base with
per-event intervals; JobSchedulerEvent; ManagedJobUpdateEvent;
ServiceUpdateEvent; AutostopEvent). The AutostopEvent here stops/terminates
the slice through the provision API using the provider recorded in the
autostop config — no Ray-YAML re-parsing and no monkey-patched `ray up`
(reference events.py:90-291).
"""
from __future__ import annotations

import itertools
import time
import traceback

import psutil

from skypilot_tpu import sky_logging
from skypilot_tpu.chaos import injector as chaos_injector
from skypilot_tpu.observability import events as obs_events
from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.skylet import job_lib

logger = sky_logging.init_logger(__name__)

# Failure backoff cap: a persistently crashing event re-fires at most
# this many intervals apart (it keeps signalling via the failure
# counter + journal instead of hammering at full rate forever).
MAX_BACKOFF_MULTIPLIER = 16
# Initial runs are spread over this many slots of each event's own
# interval so daemon start doesn't fire every event on the first tick.
_STAGGER_SLOTS = 8


def _pid_alive(pid) -> bool:
    if not pid or pid <= 0:
        return False
    try:
        proc = psutil.Process(int(pid))
        return proc.is_running() and \
            proc.status() != psutil.STATUS_ZOMBIE
    except (psutil.NoSuchProcess, psutil.AccessDenied, ValueError):
        return False


class SkyletEvent:
    """Base: `run()` is invoked every EVENT_INTERVAL_SECONDS ticks.

    Initial runs are staggered (event k of the daemon first fires
    ~k/8 of its interval after start — `_last_run_at = 0.0` used to
    make every event fire on the first tick simultaneously), failures
    back off exponentially up to MAX_BACKOFF_MULTIPLIER × interval,
    and every run is journaled with its duration plus counted in
    `skytpu_skylet_tick_seconds` / `skytpu_skylet_event_failures_total`.
    """
    EVENT_INTERVAL_SECONDS = 300

    _instance_counter = itertools.count()

    def __init__(self) -> None:
        idx = next(SkyletEvent._instance_counter)
        stagger = ((idx % _STAGGER_SLOTS) / _STAGGER_SLOTS *
                   self.EVENT_INTERVAL_SECONDS)
        self._last_run_at = (time.time() - self.EVENT_INTERVAL_SECONDS +
                             stagger)
        self._consecutive_failures = 0

    def current_interval(self) -> float:
        """Seconds between runs, inflated while the event is failing."""
        if self._consecutive_failures == 0:
            return float(self.EVENT_INTERVAL_SECONDS)
        return float(self.EVENT_INTERVAL_SECONDS * min(
            2**self._consecutive_failures, MAX_BACKOFF_MULTIPLIER))

    def maybe_run(self) -> None:
        now = time.time()
        if now - self._last_run_at < self.current_interval():
            return
        self._last_run_at = now
        name = type(self).__name__
        t0 = time.perf_counter()
        try:
            # Chaos site: a raise counts as an event failure, exercising
            # the exponential failure backoff below.
            chaos_injector.inject('skylet.tick', event=name)
            self.run()
        except Exception:  # pylint: disable=broad-except
            self._consecutive_failures += 1
            duration = time.perf_counter() - t0
            obs_events.skylet_event_failures().labels(event=name).inc()
            self._record_tick(name, duration, 'fail')
            logger.error(
                f'{name} failed ({self._consecutive_failures} '
                f'consecutive; next attempt in '
                f'{self.current_interval():.0f}s):\n'
                f'{traceback.format_exc()}')
        else:
            self._consecutive_failures = 0
            self._record_tick(name, time.perf_counter() - t0, 'ok')

    def _record_tick(self, name: str, duration: float,
                     status: str) -> None:
        obs_events.skylet_tick_hist().labels(event=name).observe(duration)
        try:
            obs_events.skylet_journal().append(
                'skylet_event', event_name=name, status=status,
                duration_s=round(duration, 6),
                consecutive_failures=self._consecutive_failures)
        except Exception:  # pylint: disable=broad-except
            pass  # the recorder must never break the event loop

    def run(self) -> None:
        raise NotImplementedError


class JobSchedulerEvent(SkyletEvent):
    """Launch queued jobs FIFO + reconcile drifted statuses."""
    EVENT_INTERVAL_SECONDS = 20

    def run(self) -> None:
        job_lib.update_job_status()
        job_lib.scheduler.schedule_step()
        if not job_lib.is_cluster_idle():
            autostop_lib.set_last_active_time_to_now()


class ManagedJobUpdateEvent(SkyletEvent):
    """Mark managed jobs whose controller process died as
    FAILED_CONTROLLER (parity: reference events.py:70-78 — an orphaned
    job would otherwise show RUNNING forever)."""
    EVENT_INTERVAL_SECONDS = 300

    def run(self) -> None:
        from skypilot_tpu.jobs import state as jobs_state  # pylint: disable=import-outside-toplevel

        for job_id in jobs_state.get_nonterminal_job_ids():
            records = jobs_state.get_job_records(job_id)
            if not records:
                continue
            pid = records[0].get('controller_pid')
            if pid is None:
                # Controller never registered; leave submission-time
                # races to the submitter.
                continue
            if _pid_alive(pid):
                continue
            logger.warning(
                f'Managed job {job_id}: controller pid {pid} is gone; '
                'marking FAILED_CONTROLLER.')
            for record in records:
                status = jobs_state.ManagedJobStatus(record['status'])
                if status.is_terminal():
                    continue
                jobs_state.set_status(
                    job_id, record['task_id'],
                    jobs_state.ManagedJobStatus.FAILED_CONTROLLER,
                    failure_reason=f'Controller process {pid} died.')


class ServiceUpdateEvent(SkyletEvent):
    """Mark services whose controller/LB process died as FAILED
    (parity: reference events.py:81-88 ServiceUpdateEvent controller
    liveness check)."""
    EVENT_INTERVAL_SECONDS = 300

    def run(self) -> None:
        from skypilot_tpu.serve import serve_state  # pylint: disable=import-outside-toplevel

        for service in serve_state.get_services():
            status = serve_state.ServiceStatus(service['status'])
            if status in (serve_state.ServiceStatus.SHUTTING_DOWN,) or \
                    status.is_terminal():
                continue
            dead = None
            for role in ('controller_pid', 'lb_pid'):
                pid = service.get(role)
                if pid is not None and not _pid_alive(pid):
                    dead = (role, pid)
                    break
            if dead is None:
                continue
            role, pid = dead
            name = service['name']
            logger.warning(f'Service {name}: {role} {pid} is gone; '
                           'marking FAILED.')
            serve_state.set_service_status(
                name, serve_state.ServiceStatus.FAILED)
            for replica in serve_state.get_replicas(name):
                rstatus = serve_state.ReplicaStatus(replica['status'])
                if rstatus.is_terminal():
                    continue
                serve_state.set_replica_status(
                    name, replica['replica_id'],
                    serve_state.ReplicaStatus.FAILED)


class AutostopEvent(SkyletEvent):
    """Stop/terminate this cluster after the configured idle window."""
    EVENT_INTERVAL_SECONDS = 60

    def run(self) -> None:
        config = autostop_lib.get_autostop_config()
        if config is None or not config.enabled:
            return
        if not job_lib.is_cluster_idle():
            return
        last_active = autostop_lib.get_last_active_time()
        idle_seconds = time.time() - last_active if last_active > 0 else 0.0
        if idle_seconds < config.autostop_idle_minutes * 60:
            return
        logger.info(
            f'Autostop: idle {idle_seconds / 60:.1f}m >= '
            f'{config.autostop_idle_minutes}m; '
            f'{"terminating" if config.down else "stopping"} '
            f'{config.cluster_name}.')
        from skypilot_tpu.provision import provisioner  # pylint: disable=import-outside-toplevel
        provisioner.teardown_cluster(config.provider_name,
                                     config.cluster_name,
                                     terminate=config.down)
