"""Orphan-reaper daemon: kills a job's process tree when its parent dies.

Parity: /root/reference/sky/skylet/subprocess_daemon.py:13-88. Spawned
detached (start_new_session) alongside every gang-supervised user process so
that `sky cancel` or a dead supervisor never leaves trainers holding TPU
chips (libtpu grabs an exclusive lock per chip; a leaked process bricks the
slice for subsequent jobs).
"""
from __future__ import annotations

import argparse
import sys
import time

import psutil


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--parent-pid', type=int, required=True)
    parser.add_argument('--proc-pid', type=int, required=True)
    args = parser.parse_args()

    try:
        process = psutil.Process(args.proc_pid)
    except psutil.NoSuchProcess:
        sys.exit(0)

    parent = None
    try:
        parent = psutil.Process(args.parent_pid)
    except psutil.NoSuchProcess:
        pass

    if parent is not None:
        try:
            parent.wait()
        except psutil.Error:
            pass

    # Parent is gone: reap the whole descendant tree, children first.
    try:
        children = process.children(recursive=True)
    except psutil.NoSuchProcess:
        sys.exit(0)
    victims = children + [process]
    for proc in victims:
        try:
            proc.terminate()
        except psutil.NoSuchProcess:
            continue
    _, alive = psutil.wait_procs(victims, timeout=5)
    for proc in alive:
        try:
            proc.kill()
        except psutil.NoSuchProcess:
            continue
    time.sleep(0.1)


if __name__ == '__main__':
    main()
