"""The skylet daemon: tiny event loop on the slice head host.

Parity: /root/reference/sky/skylet/skylet.py:1-33 (infinite loop over
events every tick).
"""
from __future__ import annotations

import time

from skypilot_tpu import sky_logging
from skypilot_tpu.skylet import constants
from skypilot_tpu.skylet import events

logger = sky_logging.init_logger(__name__)

EVENTS = (
    events.JobSchedulerEvent(),
    events.ManagedJobUpdateEvent(),
    events.ServiceUpdateEvent(),
    events.AutostopEvent(),
)


def main() -> None:
    logger.info('skylet started.')
    while True:
        time.sleep(constants.SKYLET_EVENT_INTERVAL_SECONDS)
        for event in EVENTS:
            event.maybe_run()


if __name__ == '__main__':
    main()
