"""Idempotent skylet (re)start, invoked on the head host at provision time.

Parity: /root/reference/sky/skylet/attempt_skylet.py:1-63. Version-stamps
the running skylet so a re-provision with newer app code restarts it.
"""
from __future__ import annotations

import os
import subprocess
import sys

import psutil

from skypilot_tpu.skylet import constants

VERSION_FILE = os.path.expanduser('~/.skytpu/skylet_version')


def _running_skylet_pid() -> int:
    pid_file = os.path.expanduser(constants.SKYLET_PID_FILE)
    try:
        with open(pid_file, encoding='utf-8') as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return -1
    try:
        proc = psutil.Process(pid)
        if 'skylet' in ' '.join(proc.cmdline()):
            return pid
    except (psutil.NoSuchProcess, psutil.AccessDenied):
        pass
    return -1


def main() -> None:
    from skypilot_tpu.utils import daemon_registry
    # Reap daemons whose home dir vanished (crash-interrupted runs)
    # before starting a new one.
    daemon_registry.reap_stale()
    pid = _running_skylet_pid()
    restart = os.environ.get('SKYTPU_RESTART_SKYLET') == '1'
    if pid > 0 and not restart:
        print(f'skylet already running (pid={pid}).')
        return
    if pid > 0:
        psutil.Process(pid).terminate()
    os.makedirs(os.path.expanduser('~/.skytpu'), exist_ok=True)
    log_file = os.path.expanduser(constants.SKYLET_LOG_FILE)
    env = dict(os.environ)
    with open(log_file, 'a', encoding='utf-8') as log:
        proc = subprocess.Popen(  # pylint: disable=consider-using-with
            [sys.executable, '-m', 'skypilot_tpu.skylet.skylet'],
            stdout=log, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, start_new_session=True, env=env)
    with open(os.path.expanduser(constants.SKYLET_PID_FILE), 'w',
              encoding='utf-8') as f:
        f.write(str(proc.pid))
    daemon_registry.register(proc.pid, 'skylet',
                             home=os.path.expanduser('~'))
    print(f'skylet started (pid={proc.pid}).')


if __name__ == '__main__':
    main()
