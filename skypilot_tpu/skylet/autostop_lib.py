"""Autostop config + activity tracking on the head host.

Parity: /root/reference/sky/skylet/autostop_lib.py:1-131. The config
additionally records the provider + cluster name so the AutostopEvent can
call the provision API directly (the reference instead re-parses the Ray
cluster YAML shipped to the head).
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

from skypilot_tpu.skylet import constants


def _config_path() -> str:
    return os.path.expanduser(constants.AUTOSTOP_CONFIG_FILE)


def _last_active_path() -> str:
    return os.path.expanduser(constants.AUTOSTOP_LAST_ACTIVE_FILE)


@dataclasses.dataclass
class AutostopConfig:
    autostop_idle_minutes: int     # <0 disables
    down: bool                     # terminate instead of stop
    provider_name: str
    cluster_name: str

    @property
    def enabled(self) -> bool:
        return self.autostop_idle_minutes >= 0


def set_autostop(idle_minutes: int, down: bool, provider_name: str,
                 cluster_name: str) -> None:
    config = AutostopConfig(idle_minutes, down, provider_name, cluster_name)
    os.makedirs(os.path.dirname(_config_path()), exist_ok=True)
    with open(_config_path(), 'w', encoding='utf-8') as f:
        json.dump(dataclasses.asdict(config), f)
    set_last_active_time_to_now()


def get_autostop_config() -> Optional[AutostopConfig]:
    if not os.path.exists(_config_path()):
        return None
    with open(_config_path(), encoding='utf-8') as f:
        return AutostopConfig(**json.load(f))


def set_last_active_time_to_now() -> None:
    os.makedirs(os.path.dirname(_last_active_path()), exist_ok=True)
    with open(_last_active_path(), 'w', encoding='utf-8') as f:
        f.write(str(time.time()))


def get_last_active_time() -> float:
    try:
        with open(_last_active_path(), encoding='utf-8') as f:
            return float(f.read().strip())
    except (OSError, ValueError):
        return -1.0
