"""Subprocess execution with log capture/streaming, and log tailing.

Parity: /root/reference/sky/skylet/log_lib.py:131-458 (`run_with_log`,
`make_task_bash_script`, `tail_logs` with follow). Used on both sides: the
client tees ssh output through it; slice hosts wrap the user command with it.
"""
from __future__ import annotations

import io
import os
import selectors
import subprocess
import sys
import tempfile
import textwrap
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple, Union

from skypilot_tpu import sky_logging
from skypilot_tpu.skylet import constants

logger = sky_logging.init_logger(__name__)

_SKY_LOG_WAITING_GAP_SECONDS = 1


def process_subprocess_stream(proc: subprocess.Popen,
                              log_path: str,
                              stream_logs: bool,
                              require_outputs: bool = False,
                              line_prefix: str = '') -> Tuple[str, str]:
    """Pump stdout/stderr of `proc` to logfile (+optionally console/RAM)."""
    stdout_io = io.StringIO() if require_outputs else None
    stderr_io = io.StringIO() if require_outputs else None
    sel = selectors.DefaultSelector()
    streams = {}
    if proc.stdout is not None:
        sel.register(proc.stdout, selectors.EVENT_READ, 'stdout')
        streams['stdout'] = stdout_io
    if proc.stderr is not None:
        sel.register(proc.stderr, selectors.EVENT_READ, 'stderr')
        streams['stderr'] = stderr_io

    os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
    with open(log_path, 'a', encoding='utf-8') as fout:
        open_count = len(sel.get_map())
        while open_count > 0:
            for key, _ in sel.select():
                line = key.fileobj.readline()
                if not line:
                    sel.unregister(key.fileobj)
                    open_count -= 1
                    continue
                name = key.data
                fout.write(line)
                fout.flush()
                mem = streams.get(name)
                if mem is not None:
                    mem.write(line)
                if stream_logs:
                    out = sys.stderr if name == 'stderr' else sys.stdout
                    out.write(line_prefix + line)
                    out.flush()
    stdout = stdout_io.getvalue() if stdout_io else ''
    stderr = stderr_io.getvalue() if stderr_io else ''
    return stdout, stderr


def run_with_log(cmd: Union[str, List[str]],
                 log_path: str,
                 *,
                 require_outputs: bool = False,
                 stream_logs: bool = False,
                 shell: bool = False,
                 with_ray: bool = False,
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 line_prefix: str = '',
                 on_spawn: Optional[Callable[['subprocess.Popen'],
                                             None]] = None,
                 **kwargs) -> Union[int, Tuple[int, str, str]]:
    """Run cmd, teeing output to `log_path`; returns rc (or rc, out, err).

    `on_spawn` (if given) receives the Popen right after launch — the
    gang supervisor uses it to hold rank handles for fail-fast kills.
    """
    del with_ray  # reference-API compat; no Ray here
    assert process_stream_ok(kwargs)
    log_path = os.path.expanduser(log_path)
    with subprocess.Popen(cmd,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE,
                          start_new_session=True,
                          shell=shell,
                          executable='/bin/bash' if shell else None,
                          text=True,
                          env=env,
                          cwd=cwd) as proc:
        if on_spawn is not None:
            on_spawn(proc)
        try:
            stdout, stderr = process_subprocess_stream(
                proc, log_path, stream_logs, require_outputs, line_prefix)
            proc.wait()
            if require_outputs:
                return proc.returncode, stdout, stderr
            return proc.returncode
        except KeyboardInterrupt:
            from skypilot_tpu.utils import subprocess_utils  # pylint: disable=import-outside-toplevel
            subprocess_utils.kill_children_processes([proc.pid], force=True)
            raise


def process_stream_ok(kwargs: dict) -> bool:
    kwargs.pop('process_stream', None)
    return not kwargs


def make_task_bash_script(codegen: str,
                          env_vars: Optional[Dict[str, str]] = None,
                          pidfile: Optional[str] = None) -> str:
    """Wrap user `run` commands in a bash script with exported env.

    Parity: reference log_lib.py:256-300 (login-shell semantics so conda/venv
    activation in ~/.bashrc applies; `set -e`-free so partial failures
    surface via exit codes, not silent aborts).

    `pidfile` (a remote path; '~' stays unquoted for expansion) records
    the script's own PID on the host it runs on, so a supervisor can
    later kill the task's process tree over the transport — killing the
    local ssh/kubectl client alone never signals the remote process.
    """
    script = [
        textwrap.dedent(f"""\
            #!/bin/bash
            source ~/.bashrc 2>/dev/null || true
            set -a
            . ~/.skytpu/task_env 2>/dev/null || true
            set +a
            cd {constants.SKY_REMOTE_WORKDIR} 2>/dev/null || cd ~
            """),
    ]
    if pidfile:
        # Handshake with make_kill_tree_command: we WRITE the pidfile
        # then READ the abort tombstone; the killer WRITES the tombstone
        # then READS the pidfile. Whatever the interleaving, at least
        # one side observes the other — an abort can never slip through
        # just because this prologue was slow to reach the echo line.
        # GC: tombstones of ranks that never consumed them (clean exits
        # swept by a gang abort) have no other deletion path; age them
        # out here so ~/.skytpu/gang cannot creep over cluster life.
        script.append(f'find "$(dirname {pidfile})" -name "*.abort" '
                      '-mtime +7 -type f -delete 2>/dev/null || true')
        script.append(f'mkdir -p "$(dirname {pidfile})" && '
                      f'echo $$ > {pidfile} && '
                      # Self-clean on normal exit so a later kill sweep
                      # cannot TERM a reused PID.
                      f"trap 'rm -f {pidfile}' EXIT; "
                      f'if [ -e {pidfile}.abort ]; then '
                      f'rm -f {pidfile} {pidfile}.abort; exit 143; fi')
    if env_vars:
        for k, v in env_vars.items():
            script.append(f'export {k}={subprocess_quote(v)}')
    script.append(codegen)
    return '\n'.join(script) + '\n'


def make_kill_tree_command(pidfile: str) -> str:
    """Shell one-liner that kills the process tree rooted at the PID in
    `pidfile`, then removes the pidfile.

    Kill order matters: TERMing a shell's *child* before the shell lets
    bash resume from `wait` and execute the task script's next command
    before its own TERM arrives (a gang-aborted `prepare && train &&
    upload` could still run `upload`). So the walk first SIGSTOPs the
    tree root-first — a stopped shell cannot resume, and a stopped
    process cannot fork new children mid-sweep — then TERMs every
    collected PID (pending while stopped), then CONTs them so the TERM
    is processed before any user code runs again.

    Slow-start race (the pidfile is not there yet because the task
    script's prologue — login shell sourcing, cd — has not reached its
    `echo $$` line): the killer first drops a `.abort` tombstone, then
    reads the pidfile once. The task prologue writes the pidfile and
    THEN checks the tombstone (make_task_bash_script) — each side
    writes before it reads, so whichever timing wins, either the killer
    sees the pidfile or the task sees the tombstone and exits 143
    before running any user command. No polling needed: a task whose
    pidfile is absent has not run user code and will stop itself. The
    tombstone is consumed by whichever side reads it (killed-task
    sweep removes it; a self-aborting prologue removes it); for ranks
    that already exited cleanly it lingers in the uniquely-tagged gang
    dir — bounded litter, never matching a future pidfile path.

    The sequence runs under `setsid` (falling back to `nohup ... &`):
    if the transport drops mid-sweep — sshd HUPs the session's process
    group on disconnect — an in-flight killer interrupted between STOP
    and TERM/CONT would otherwise strand the task tree frozen forever.
    Detached from the session/group, the killer finishes regardless.
    """
    seq = (f'mkdir -p "$(dirname {pidfile})"; touch {pidfile}.abort; '
           f'pid=$(cat {pidfile} 2>/dev/null); '
           'if [ -n "$pid" ]; then '
           'stop_tree() { local c; kill -STOP "$1" 2>/dev/null; '
           'pids="$pids $1"; '
           'for c in $(pgrep -P "$1" 2>/dev/null); do stop_tree "$c"; '
           'done; }; '
           f'pids=""; stop_tree "$pid"; '
           'kill -TERM $pids 2>/dev/null; '
           'kill -CONT $pids 2>/dev/null; '
           f'rm -f {pidfile} {pidfile}.abort; fi')
    quoted = subprocess_quote(seq)
    # setsid detaches session+group; where absent (minimal containers),
    # nohup+background at least survives the HUP a dropped ssh session
    # delivers. Callers treat the kill as best-effort either way.
    return (f'if command -v setsid >/dev/null 2>&1; '
            f'then setsid bash -c {quoted}; '
            f'else nohup bash -c {quoted} >/dev/null 2>&1 & fi')


def subprocess_quote(s: str) -> str:
    import shlex  # pylint: disable=import-outside-toplevel
    return shlex.quote(str(s))


def run_bash_command_with_log(bash_command: str,
                              log_path: str,
                              env_vars: Optional[Dict[str, str]] = None,
                              stream_logs: bool = False,
                              line_prefix: str = '') -> int:
    """Materialize a script file then run it with logging (host-side exec)."""
    with tempfile.NamedTemporaryFile('w', prefix='sky_app_', suffix='.sh',
                                     delete=False) as fp:
        fp.write(make_task_bash_script(bash_command, env_vars))
        script_path = fp.name
    os.chmod(script_path, 0o755)
    try:
        return run_with_log(f'/bin/bash {script_path}', log_path, shell=True,
                            stream_logs=stream_logs, line_prefix=line_prefix)  # type: ignore[return-value]
    finally:
        try:
            os.remove(script_path)
        except OSError:
            pass


def _follow_file(f, exit_when) -> Iterator[str]:
    while True:
        line = f.readline()
        if line:
            yield line
        else:
            if exit_when():
                # Drain anything written between the check and now.
                rest = f.read()
                if rest:
                    yield rest
                return
            time.sleep(_SKY_LOG_WAITING_GAP_SECONDS)


def tail_logs(job_id: Optional[int],
              log_dir: Optional[str],
              follow: bool = True,
              tail: int = 0) -> int:
    """Print a job's run.log; optionally follow until the job terminates.

    Parity: reference log_lib.py:331-458. Returns the job's exit-ish status
    code (0 on SUCCEEDED).
    """
    from skypilot_tpu.skylet import job_lib  # pylint: disable=import-outside-toplevel
    if log_dir is None:
        print(f'Job {job_id} not found (see `sky queue`).', file=sys.stderr)
        return 1
    log_path = os.path.join(os.path.expanduser(log_dir), 'run.log')
    deadline = time.time() + 60
    while not os.path.exists(log_path):
        if time.time() > deadline:
            print(f'Log file not found: {log_path}', file=sys.stderr)
            return 1
        status = job_lib.get_status(job_id) if job_id is not None else None
        if status is not None and status.is_terminal():
            break
        time.sleep(_SKY_LOG_WAITING_GAP_SECONDS)
    if not os.path.exists(log_path):
        return 0

    def _job_done() -> bool:
        if job_id is None:
            return True
        status = job_lib.get_status(job_id)
        return status is None or status.is_terminal()

    with open(log_path, 'r', encoding='utf-8', errors='replace') as f:
        if tail > 0:
            lines = f.readlines()[-tail:]
            for line in lines:
                print(line, end='')
        if follow:
            if tail == 0:
                for line in f:
                    print(line, end='')
            for line in _follow_file(f, _job_done):
                print(line, end='', flush=True)
        elif tail == 0:
            for line in f:
                print(line, end='')
    if job_id is not None:
        status = job_lib.get_status(job_id)
        return 0 if status == job_lib.JobStatus.SUCCEEDED else 1
    return 0
