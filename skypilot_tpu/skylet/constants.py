"""Remote-runtime constants: paths, env-var names, bootstrap commands.

Parity: /root/reference/sky/skylet/constants.py:1-291 — with the Ray-specific
pieces (SKY_REMOTE_RAY_PORT, ray launcher shims) replaced by the TPU job
contract: rank/host-list env plus JAX coordinator variables, so user code can
call `jax.distributed.initialize()` with zero glue.
"""
from __future__ import annotations

SKYTPU_REMOTE_HOME = '~/.skytpu'
SKY_LOGS_DIRECTORY = '~/sky_logs'
SKY_REMOTE_WORKDIR = '~/sky_workdir'
SKY_REMOTE_APP_DIR = '~/.skytpu/app'
SKY_REMOTE_PACKAGE_DIR = '~/.skytpu/wheels'

JOB_DB_PATH = '~/.skytpu/jobs.db'
SKYLET_PID_FILE = '~/.skytpu/skylet.pid'
SKYLET_LOG_FILE = '~/.skytpu/skylet.log'
AUTOSTOP_CONFIG_FILE = '~/.skytpu/autostop_config.json'
AUTOSTOP_LAST_ACTIVE_FILE = '~/.skytpu/autostop_last_active'

# --- The TPU job contract: env exported to every task process. ---
# Gang identity (parity with SKYPILOT_NODE_RANK/NODE_IPS/NUM_NODES,
# reference cloud_vm_ray_backend.py:579-634).
ENV_HOST_RANK = 'SKYTPU_HOST_RANK'          # global host rank, 0..N-1
ENV_HOST_IPS = 'SKYTPU_HOST_IPS'            # newline-separated, rank order
ENV_NUM_HOSTS = 'SKYTPU_NUM_HOSTS'
ENV_NUM_SLICES = 'SKYTPU_NUM_SLICES'        # multislice (DCN) width
ENV_SLICE_ID = 'SKYTPU_SLICE_ID'            # which slice this host is in
ENV_TASK_ID = 'SKYTPU_TASK_ID'              # globally unique task run id
ENV_CLUSTER_NAME = 'SKYTPU_CLUSTER_NAME'
ENV_JOB_ID = 'SKYTPU_JOB_ID'
# JAX coordination (consumed by jax.distributed.initialize / libtpu).
ENV_COORDINATOR_ADDRESS = 'SKYTPU_COORDINATOR_ADDRESS'  # host0_ip:port
ENV_ACCEL_TYPE = 'SKYTPU_ACCELERATOR_TYPE'  # e.g. tpu-v5e-16
ENV_TOPOLOGY = 'SKYTPU_TOPOLOGY'            # e.g. 4x4 / 2x2x4
ENV_CHIPS_PER_HOST = 'SKYTPU_CHIPS_PER_HOST'
# Checkpoint contract (first-class, unlike the reference — SURVEY.md §5):
# a per-job directory (bucket-mounted when storage is configured) that
# trainers should write orbax checkpoints into; managed-jobs recovery
# relaunches with the same path so auto-resume is a convention, not code.
ENV_CHECKPOINT_DIR = 'SKYTPU_CHECKPOINT_DIR'

JAX_COORDINATOR_PORT = 8476
SKYLET_EVENT_INTERVAL_SECONDS = 20

# Default container-side python. Overridable because local (hermetic) hosts
# share the client's interpreter.
SKY_PYTHON_CMD = 'python3'

# Bootstrap run on every fresh host before the skylet starts: make dirs,
# ensure the app package is importable. The app package is rsynced (not
# pip-wheel-installed as the reference does, cloud_vm_ray_backend.py:2748) —
# rsync of the package tree has the same idempotency with less latency.
RUNTIME_SETUP_COMMANDS = (
    f'mkdir -p {SKY_LOGS_DIRECTORY} {SKY_REMOTE_WORKDIR} '
    f'{SKYTPU_REMOTE_HOME}; true')

SKYLET_START_COMMAND = (
    f'cd ~ && PYTHONPATH={SKY_REMOTE_APP_DIR}:$PYTHONPATH '
    f'nohup {SKY_PYTHON_CMD} -m skypilot_tpu.skylet.attempt_skylet '
    f'>> {SKYLET_LOG_FILE} 2>&1')

# Reference parity names kept importable for task authors migrating over.
LEGACY_ENV_ALIASES = {
    'SKYPILOT_NODE_RANK': ENV_HOST_RANK,
    'SKYPILOT_NODE_IPS': ENV_HOST_IPS,
    'SKYPILOT_NUM_NODES': ENV_NUM_HOSTS,
    'SKYPILOT_TASK_ID': ENV_TASK_ID,
}
