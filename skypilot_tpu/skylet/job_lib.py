"""On-cluster job queue: sqlite-backed FIFO with status reconciliation.

Parity: /root/reference/sky/skylet/job_lib.py:101-939 (JobStatus lifecycle,
FIFOScheduler, update_job_status reconciliation, is_cluster_idle for
autostop, JobLibCodeGen). TPU-first difference: jobs are executed by the
framework's own gang supervisor (skypilot_tpu.backends.gang_exec run on the
head host) instead of `ray job submit`; the queue tracks the supervisor PID
and reconciles by liveness probe, not Ray job states.
"""
from __future__ import annotations

import enum
import getpass
import json
import os
import shlex
import signal
import sqlite3
import subprocess
import time
from typing import Any, Dict, List, Optional

import psutil

from skypilot_tpu import sky_logging
from skypilot_tpu.skylet import constants

logger = sky_logging.init_logger(__name__)


def _db_path() -> str:
    path = os.environ.get('SKYTPU_JOB_DB',
                          os.path.expanduser(constants.JOB_DB_PATH))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    return path


_CREATE = """\
CREATE TABLE IF NOT EXISTS jobs (
    job_id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_name TEXT,
    username TEXT,
    submitted_at REAL,
    status TEXT,
    run_timestamp TEXT,
    start_at REAL DEFAULT -1,
    end_at REAL DEFAULT NULL,
    resources TEXT,
    pid INTEGER DEFAULT -1,
    run_cmd TEXT,
    log_dir TEXT);
CREATE TABLE IF NOT EXISTS pending_jobs (
    job_id INTEGER PRIMARY KEY,
    run_cmd TEXT,
    submit REAL,
    created_time REAL);
"""


_initialized_paths: set = set()


def _conn() -> sqlite3.Connection:
    path = _db_path()
    # Schema DDL (and its implicit COMMIT) only once per db per process;
    # keyed by path because tests repoint SKYTPU_JOB_DB. Re-run it if the
    # file vanished (connect() recreates an empty, schema-less db).
    needs_ddl = path not in _initialized_paths or not os.path.exists(path)
    conn = sqlite3.connect(path, timeout=10)
    if needs_ddl:
        conn.executescript(_CREATE)
        _initialized_paths.add(path)
    return conn


class JobStatus(enum.Enum):
    """Job lifecycle (parity: reference job_lib.py:101-160)."""
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    FAILED_DRIVER = 'FAILED_DRIVER'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'

    @classmethod
    def nonterminal_statuses(cls) -> List['JobStatus']:
        return [cls.INIT, cls.PENDING, cls.SETTING_UP, cls.RUNNING]

    def is_terminal(self) -> bool:
        return self not in self.nonterminal_statuses()

    def __lt__(self, other: 'JobStatus') -> bool:
        order = list(JobStatus)
        return order.index(self) < order.index(other)

    def colored_str(self) -> str:
        color = {
            JobStatus.SUCCEEDED: '\x1b[32m',
            JobStatus.FAILED: '\x1b[31m',
            JobStatus.FAILED_SETUP: '\x1b[31m',
            JobStatus.FAILED_DRIVER: '\x1b[31m',
            JobStatus.CANCELLED: '\x1b[33m',
        }.get(self, '\x1b[36m')
        return f'{color}{self.value}\x1b[0m'


# ------------------------------------------------------------------ CRUD


def add_job(job_name: str, username: str, run_timestamp: str,
            resources_str: str) -> int:
    """Insert a job in INIT; returns its id. Called before codegen exec."""
    with _conn() as conn:
        cur = conn.execute(
            'INSERT INTO jobs (job_name, username, submitted_at, status, '
            'run_timestamp, resources, log_dir) VALUES (?, ?, ?, ?, ?, ?, ?)',
            (job_name, username, time.time(), JobStatus.INIT.value,
             run_timestamp, resources_str,
             os.path.join(constants.SKY_LOGS_DIRECTORY, run_timestamp)))
        return int(cur.lastrowid)


def set_status(job_id: int, status: JobStatus) -> None:
    with _conn() as conn:
        if status == JobStatus.RUNNING:
            conn.execute(
                'UPDATE jobs SET status=?, start_at=? WHERE job_id=?',
                (status.value, time.time(), job_id))
        elif status.is_terminal():
            conn.execute(
                'UPDATE jobs SET status=?, end_at=? WHERE job_id=? ',
                (status.value, time.time(), job_id))
        else:
            conn.execute('UPDATE jobs SET status=? WHERE job_id=?',
                         (status.value, job_id))


def set_job_started(job_id: int) -> None:
    set_status(job_id, JobStatus.RUNNING)


def set_pid(job_id: int, pid: int) -> None:
    with _conn() as conn:
        conn.execute('UPDATE jobs SET pid=? WHERE job_id=?', (pid, job_id))


def get_status(job_id: int) -> Optional[JobStatus]:
    with _conn() as conn:
        row = conn.execute('SELECT status FROM jobs WHERE job_id=?',
                           (job_id,)).fetchone()
    return JobStatus(row[0]) if row else None


def get_record(job_id: int) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        row = conn.execute(
            'SELECT job_id, job_name, username, submitted_at, status, '
            'run_timestamp, start_at, end_at, resources, pid, log_dir '
            'FROM jobs WHERE job_id=?', (job_id,)).fetchone()
    if row is None:
        return None
    return _record(row)


def _record(row: tuple) -> Dict[str, Any]:
    return {
        'job_id': row[0],
        'job_name': row[1],
        'username': row[2],
        'submitted_at': row[3],
        'status': JobStatus(row[4]),
        'run_timestamp': row[5],
        'start_at': row[6],
        'end_at': row[7],
        'resources': row[8],
        'pid': row[9],
        'log_dir': row[10],
    }


def get_jobs(statuses: Optional[List[JobStatus]] = None,
             limit: Optional[int] = None) -> List[Dict[str, Any]]:
    q = ('SELECT job_id, job_name, username, submitted_at, status, '
         'run_timestamp, start_at, end_at, resources, pid, log_dir FROM jobs')
    params: list = []
    if statuses:
        q += ' WHERE status IN (%s)' % ','.join('?' * len(statuses))
        params += [s.value for s in statuses]
    q += ' ORDER BY job_id DESC'
    if limit:
        q += ' LIMIT ?'
        params.append(limit)
    with _conn() as conn:
        rows = conn.execute(q, params).fetchall()
    return [_record(r) for r in rows]


def get_latest_job_id() -> Optional[int]:
    with _conn() as conn:
        row = conn.execute('SELECT MAX(job_id) FROM jobs').fetchone()
    return row[0] if row and row[0] is not None else None


def get_log_dir_for_job(job_id: int) -> Optional[str]:
    rec = get_record(job_id)
    return rec['log_dir'] if rec else None


def run_timestamp_with_fallback(job_id: Optional[int]) -> Optional[str]:
    if job_id is None:
        job_id = get_latest_job_id()
        if job_id is None:
            return None
    rec = get_record(job_id)
    return rec['run_timestamp'] if rec else None


# ------------------------------------------------------------- scheduler


class FIFOScheduler:
    """Launch queued jobs in submit order, one pass per invocation.

    Parity: reference job_lib.py:163-217. The queued command is the gang
    supervisor invocation (a shell line); we spawn it detached and record
    its PID for liveness reconciliation.
    """

    ALIVE_STATUSES = (JobStatus.SETTING_UP, JobStatus.RUNNING)

    def queue(self, job_id: int, cmd: str) -> None:
        with _conn() as conn:
            conn.execute(
                'INSERT OR REPLACE INTO pending_jobs VALUES (?, ?, 0, ?)',
                (job_id, cmd, time.time()))
        set_status(job_id, JobStatus.PENDING)
        self.schedule_step()

    def remove_job_no_lock(self, job_id: int) -> None:
        with _conn() as conn:
            conn.execute('DELETE FROM pending_jobs WHERE job_id=?', (job_id,))

    def _get_pending_job(self) -> Optional[tuple]:
        with _conn() as conn:
            return conn.execute(
                'SELECT job_id, run_cmd FROM pending_jobs WHERE submit=0 '
                'ORDER BY job_id ASC LIMIT 1').fetchone()

    def schedule_step(self) -> None:
        # Strictly FIFO: launch the oldest pending job; one at a time on
        # the slice (a TPU slice runs one gang job at a time — chips are
        # exclusive, unlike fractional GPUs).
        alive = get_jobs(list(self.ALIVE_STATUSES))
        if alive:
            return
        row = self._get_pending_job()
        if row is None:
            return
        job_id, run_cmd = row
        status = get_status(job_id)
        if status is None or status != JobStatus.PENDING:
            self.remove_job_no_lock(job_id)
            return self.schedule_step()
        with _conn() as conn:
            conn.execute('UPDATE pending_jobs SET submit=? WHERE job_id=?',
                         (time.time(), job_id))
        proc = subprocess.Popen(run_cmd,
                                shell=True,
                                executable='/bin/bash',
                                stdin=subprocess.DEVNULL,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL,
                                start_new_session=True)
        from skypilot_tpu.utils import daemon_registry  # pylint: disable=import-outside-toplevel
        daemon_registry.register(proc.pid, 'job-supervisor',
                                 home=os.path.expanduser('~'))
        # Ordering matters twice over: (1) pid is written before the status
        # leaves PENDING, so a concurrent update_job_status can never see
        # SETTING_UP with pid=-1 (would mark the job FAILED_DRIVER);
        # (2) the status write is guarded on still-PENDING, so if the (very
        # fast) supervisor already advanced to RUNNING/terminal, we do not
        # regress its status.
        with _conn() as conn:
            conn.execute('UPDATE jobs SET pid=? WHERE job_id=?',
                         (proc.pid, job_id))
            conn.execute('UPDATE jobs SET status=? WHERE job_id=? '
                         'AND status=?',
                         (JobStatus.SETTING_UP.value, job_id,
                          JobStatus.PENDING.value))
        self.remove_job_no_lock(job_id)


scheduler = FIFOScheduler()


# --------------------------------------------------------- reconciliation


def update_job_status(job_ids: Optional[List[int]] = None) -> None:
    """Fix statuses that have drifted from reality (dead supervisors).

    Parity: reference job_lib.py:527-650 (reconciles against Ray job
    states); here the source of truth is supervisor-PID liveness.
    """
    if job_ids is None:
        job_ids = [r['job_id'] for r in get_jobs(JobStatus.nonterminal_statuses())]
    for job_id in job_ids:
        rec = get_record(job_id)
        if rec is None or rec['status'].is_terminal():
            continue
        pid = rec['pid']
        if rec['status'] in (JobStatus.INIT, JobStatus.PENDING):
            # Not yet scheduled; stale if pending for > 24h.
            if time.time() - rec['submitted_at'] > 86400:
                set_status(job_id, JobStatus.FAILED_DRIVER)
            continue
        if pid <= 0 or not psutil.pid_exists(pid):
            # Supervisor died without setting a terminal state.
            set_status(job_id, JobStatus.FAILED_DRIVER)


def is_cluster_idle() -> bool:
    """True iff no nonterminal jobs exist (consulted by autostop)."""
    return not get_jobs(JobStatus.nonterminal_statuses(), limit=1)


def cancel_jobs(job_ids: Optional[List[int]] = None,
                cancel_all: bool = False) -> List[int]:
    """Kill supervisors (whole process trees) and mark CANCELLED."""
    if cancel_all:
        records = get_jobs(JobStatus.nonterminal_statuses())
    elif job_ids:
        records = [r for jid in job_ids if (r := get_record(jid)) is not None]
    else:
        latest = get_latest_job_id()
        records = [get_record(latest)] if latest else []
    cancelled = []
    for rec in records:
        if rec is None or rec['status'].is_terminal():
            continue
        scheduler.remove_job_no_lock(rec['job_id'])
        pid = rec['pid']
        if pid > 0 and psutil.pid_exists(pid):
            from skypilot_tpu.utils import subprocess_utils  # pylint: disable=import-outside-toplevel
            try:
                os.killpg(os.getpgid(pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                subprocess_utils.kill_children_processes([pid], force=True)
        set_status(rec['job_id'], JobStatus.CANCELLED)
        cancelled.append(rec['job_id'])
    return cancelled


def fail_all_jobs_in_progress() -> None:
    for rec in get_jobs(JobStatus.nonterminal_statuses()):
        set_status(rec['job_id'], JobStatus.FAILED_DRIVER)


def format_job_queue(records: List[Dict[str, Any]]) -> str:
    lines = [f'{"ID":<5}{"NAME":<18}{"SUBMITTED":<22}{"STATUS":<15}{"LOG":<40}']
    for r in records:
        submitted = time.strftime('%Y-%m-%d %H:%M:%S',
                                  time.localtime(r['submitted_at']))
        lines.append(f'{r["job_id"]:<5}{(r["job_name"] or "-")[:17]:<18}'
                     f'{submitted:<22}{r["status"].value:<15}'
                     f'{(r["log_dir"] or "-"):<40}')
    return '\n'.join(lines)


# ------------------------------------------------------------- codegen


class JobLibCodeGen:
    """Generate python one-liners executed on the head host over ssh.

    Parity: reference job_lib.py:818-939. ssh + codegen is the client↔head
    RPC layer: no persistent service needed.
    """

    _PREFIX = ('import os; '
               "os.environ.setdefault('PYTHONUNBUFFERED','1'); "
               'from skypilot_tpu.skylet import job_lib, log_lib')

    @classmethod
    def _build(cls, code: List[str]) -> str:
        full = '; '.join([cls._PREFIX] + code)
        python = constants.SKY_PYTHON_CMD
        app_dir = constants.SKY_REMOTE_APP_DIR
        return (f'PYTHONPATH={app_dir}:$PYTHONPATH {python} -u -c '
                f'{shlex.quote(full)}')

    @classmethod
    def add_job(cls, job_name: Optional[str], username: str,
                run_timestamp: str, resources_str: str) -> str:
        name = job_name or '-'
        return cls._build([
            f'job_id = job_lib.add_job({name!r}, {username!r}, '
            f'{run_timestamp!r}, {resources_str!r})',
            'print("job_id=" + str(job_id), flush=True)',
        ])

    @classmethod
    def queue_job(cls, job_id: int, cmd: str) -> str:
        return cls._build([f'job_lib.scheduler.queue({job_id}, {cmd!r})'])

    @classmethod
    def update_status(cls) -> str:
        return cls._build(['job_lib.update_job_status()'])

    @classmethod
    def get_job_queue(cls, all_jobs: bool = True) -> str:
        statuses = (None if all_jobs else
                    '[job_lib.JobStatus(s) for s in '
                    f'{[s.value for s in JobStatus.nonterminal_statuses()]}]')
        return cls._build([
            'job_lib.update_job_status()',
            f'records = job_lib.get_jobs({statuses})',
            'import json',
            'print("JOBS:" + json.dumps([{k: (v.value if hasattr(v, "value") '
            'else v) for k, v in r.items()} for r in records]), flush=True)',
        ])

    @classmethod
    def cancel_jobs(cls, job_ids: Optional[List[int]],
                    cancel_all: bool = False) -> str:
        return cls._build([
            f'cancelled = job_lib.cancel_jobs({job_ids!r}, {cancel_all})',
            'import json; print("CANCELLED:" + json.dumps(cancelled), flush=True)',
        ])

    @classmethod
    def tail_logs(cls, job_id: Optional[int], follow: bool = True,
                  tail: int = 0) -> str:
        return cls._build([
            f'job_id = {job_id!r}',
            'job_id = job_lib.get_latest_job_id() if job_id is None else job_id',
            'log_dir = job_lib.get_log_dir_for_job(job_id) '
            'if job_id is not None else None',
            f'import sys; sys.exit(log_lib.tail_logs(job_id, log_dir, '
            f'follow={follow}, tail={tail}))',
        ])

    @classmethod
    def get_log_dir(cls, job_id: Optional[int] = None) -> str:
        return cls._build([
            f'job_id = {job_id!r}',
            'job_id = job_lib.get_latest_job_id() if job_id is None else job_id',
            'log_dir = job_lib.get_log_dir_for_job(job_id) '
            'if job_id is not None else None',
            'import json; print("LOG_DIR:" + json.dumps(log_dir), flush=True)',
        ])

    @classmethod
    def get_job_status(cls, job_ids: Optional[List[int]] = None) -> str:
        return cls._build([
            'job_lib.update_job_status()',
            f'ids = {job_ids!r} or ([job_lib.get_latest_job_id()] '
            'if job_lib.get_latest_job_id() else [])',
            'import json',
            'print("STATUS:" + json.dumps({str(i): (job_lib.get_status(i).value'
            ' if job_lib.get_status(i) else None) for i in ids}), flush=True)',
        ])


def parse_job_id(stdout: str) -> int:
    for line in stdout.splitlines():
        if line.startswith('job_id='):
            return int(line.split('=', 1)[1])
    raise ValueError(f'Could not parse job id from: {stdout!r}')


def parse_tagged_json(stdout: str, tag: str) -> Any:
    for line in stdout.splitlines():
        if line.startswith(tag):
            return json.loads(line[len(tag):])
    raise ValueError(f'No {tag} line in: {stdout!r}')


def get_current_username() -> str:
    try:
        return getpass.getuser()
    except OSError:
        return 'unknown'
