"""Layered user configuration.

Parity: /root/reference/sky/skypilot_config.py:1-259 (YAML config loaded at
import, `get_nested` with task-level override keys, jsonschema validation).
Config file: ``$SKYTPU_HOME/config.yaml`` (env override ``SKYTPU_CONFIG``).
"""
from __future__ import annotations

import copy
import os
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.utils import common_utils

# Keys a task YAML's `experimental.config_overrides` may override.
OVERRIDEABLE_CONFIG_KEYS: Tuple[Tuple[str, ...], ...] = (
    ('gcp', 'labels'),
    ('gcp', 'managed_instance_group'),
    ('tpu', 'runtime_version'),
    ('tpu', 'provision_mode'),
    ('jobs', 'controller', 'resources'),
    ('serve', 'controller', 'resources'),
    ('nvidia_gpus', 'disable'),
)

_lock = threading.Lock()
_dict: Optional[Dict[str, Any]] = None
_loaded_path: Optional[str] = None


def _config_path() -> str:
    env = os.environ.get('SKYTPU_CONFIG')
    if env:
        return os.path.expanduser(env)
    return os.path.join(common_utils.skytpu_home(), 'config.yaml')


def _validate(config: Dict[str, Any], path: str) -> None:
    try:
        import jsonschema  # pylint: disable=import-outside-toplevel
    except ImportError:
        return
    from skypilot_tpu.utils import schemas  # pylint: disable=import-outside-toplevel
    try:
        jsonschema.validate(config, schemas.get_config_schema())
    except jsonschema.ValidationError as e:
        raise exceptions.InvalidSkyTpuConfigError(
            f'Invalid config {path}: {e.message}') from e


def _load() -> Dict[str, Any]:
    global _dict, _loaded_path
    path = _config_path()
    with _lock:
        if _dict is not None and _loaded_path == path:
            return _dict
        if os.path.exists(path):
            # skytpu: lint-ok[blocking-under-lock] reason=one-time lazy load of a small local YAML; the lock is what makes the cache fill once instead of per-thread
            config = common_utils.read_yaml(path)
            _validate(config, path)
            _dict = config
        else:
            _dict = {}
        _loaded_path = path
        return _dict


def reload_config() -> None:
    """Drop the cache; next access re-reads from disk (used by tests/CLI)."""
    global _dict, _loaded_path
    with _lock:
        _dict = None
        _loaded_path = None


def loaded() -> bool:
    return bool(_load())


def get_nested(keys: Iterable[str],
               default_value: Any = None,
               override_configs: Optional[Dict[str, Any]] = None) -> Any:
    """Fetch config[k0][k1]... with optional per-task overrides applied."""
    config = copy.deepcopy(_load())
    if override_configs:
        config = _recursive_update(config, override_configs,
                                   allowed=OVERRIDEABLE_CONFIG_KEYS)
    cur = config
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return default_value
        cur = cur[k]
    return cur


def set_nested(keys: Iterable[str], value: Any) -> None:
    """In-memory override (tests / controller-side mutation)."""
    config = _load()
    with _lock:
        cur = config
        keys = list(keys)
        for k in keys[:-1]:
            cur = cur.setdefault(k, {})
        cur[keys[-1]] = value


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_load())


def _recursive_update(base: Dict[str, Any], overrides: Dict[str, Any],
                      allowed: Tuple[Tuple[str, ...], ...],
                      prefix: Tuple[str, ...] = ()) -> Dict[str, Any]:
    for k, v in overrides.items():
        key_path = prefix + (k,)
        permitted = any(key_path == a[:len(key_path)] for a in allowed)
        if not permitted:
            raise exceptions.InvalidSkyTpuConfigError(
                f'Config key {".".join(key_path)} may not be overridden by a '
                f'task. Overridable keys: '
                f'{[".".join(a) for a in OVERRIDEABLE_CONFIG_KEYS]}')
        is_prefix_of_longer = any(
            len(a) > len(key_path) and a[:len(key_path)] == key_path
            for a in allowed)
        if isinstance(v, dict) and is_prefix_of_longer:
            sub = base.get(k)
            if not isinstance(sub, dict):
                sub = {}
            base[k] = _recursive_update(sub, v, allowed, key_path)
        else:
            base[k] = v
    return base
