"""Dag: a DAG of Tasks with a thread-local `with` context.

Parity: /root/reference/sky/dag.py:1-101 — same surface (add/remove,
`is_chain`, context manager) without the networkx dependency: the graph is
small (tasks in a pipeline), so plain adjacency sets suffice.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from skypilot_tpu import task as task_lib

_thread_local = threading.local()


def get_current_dag() -> Optional['Dag']:
    stack = getattr(_thread_local, 'dag_stack', None)
    if not stack:
        return None
    return stack[-1]


class Dag:
    def __init__(self, name: Optional[str] = None) -> None:
        self.name = name
        self.tasks: List[task_lib.Task] = []
        self._edges: Dict[task_lib.Task, Set[task_lib.Task]] = {}

    def add(self, task: task_lib.Task) -> None:
        if task not in self.tasks:
            self.tasks.append(task)
            self._edges.setdefault(task, set())

    def remove(self, task: task_lib.Task) -> None:
        self.tasks.remove(task)
        self._edges.pop(task, None)
        for dsts in self._edges.values():
            dsts.discard(task)

    def add_edge(self, src: task_lib.Task, dst: task_lib.Task) -> None:
        self.add(src)
        self.add(dst)
        self._edges[src].add(dst)

    def successors(self, task: task_lib.Task) -> List[task_lib.Task]:
        return [t for t in self.tasks if t in self._edges.get(task, ())]

    def predecessors(self, task: task_lib.Task) -> List[task_lib.Task]:
        return [t for t in self.tasks if task in self._edges.get(t, ())]

    def in_degree(self, task: task_lib.Task) -> int:
        return len(self.predecessors(task))

    def out_degree(self, task: task_lib.Task) -> int:
        return len(self._edges.get(task, ()))

    def topological_order(self) -> List[task_lib.Task]:
        order: List[task_lib.Task] = []
        indeg = {t: self.in_degree(t) for t in self.tasks}
        ready = [t for t in self.tasks if indeg[t] == 0]
        while ready:
            t = ready.pop(0)
            order.append(t)
            for s in self.successors(t):
                indeg[s] -= 1
                if indeg[s] == 0:
                    ready.append(s)
        if len(order) != len(self.tasks):
            raise ValueError('Dag has a cycle.')
        return order

    def is_chain(self) -> bool:
        if len(self.tasks) <= 1:
            return True
        num_roots = sum(1 for t in self.tasks if self.in_degree(t) == 0)
        return num_roots == 1 and all(
            self.out_degree(t) <= 1 and self.in_degree(t) <= 1
            for t in self.tasks)

    def __len__(self) -> int:
        return len(self.tasks)

    def __enter__(self) -> 'Dag':
        stack = getattr(_thread_local, 'dag_stack', None)
        if stack is None:
            stack = []
            _thread_local.dag_stack = stack
        stack.append(self)
        return self

    def __exit__(self, *args) -> None:
        _thread_local.dag_stack.pop()

    def __repr__(self) -> str:
        return f'<Dag {self.name or "<unnamed>"} tasks={len(self.tasks)}>'
