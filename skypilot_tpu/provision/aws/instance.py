"""AWS EC2 provisioner: GPU/CPU VMs as the fungible GPU alternative.

Parity: /root/reference/sky/provision/aws/ (boto3) — rebuilt on the aws
CLI's JSON output with an injectable runner (`set_cli_runner`), the
same no-SDK seam as provision/gcp/tpu_api.py and data_transfer.py, so
the whole flow is unit-testable without credentials or network.

Cluster membership is tag-based (`skytpu-cluster=<name>`, per-node
`skytpu-rank`), the reference's own scheme.  Gang semantics: one
run-instances call creates all nodes; any shortfall terminates the
partial set and raises (all-or-nothing, like TPU slices).
"""
from __future__ import annotations

import json
import subprocess
import time
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.status_lib import ClusterStatus
from skypilot_tpu.utils import command_runner

logger = sky_logging.init_logger(__name__)

_CLUSTER_TAG = 'skytpu-cluster'
_RANK_TAG = 'skytpu-rank'
_KEY_NAME = 'skytpu-key'
_SG_NAME = 'skytpu-sg'
DEFAULT_SSH_USER = 'ubuntu'
# Canonical's SSM alias for the current Ubuntu 22.04 x86 AMI.
_UBUNTU_SSM = ('/aws/service/canonical/ubuntu/server/22.04/stable/'
               'current/amd64/hvm/ebs-gp3/ami-id')

# CLI seam: runner(args: List[str]) -> (returncode, stdout, stderr).
CliRunner = Callable[[List[str]], tuple]


def _default_cli_runner(args: List[str]) -> tuple:
    proc = subprocess.run(args, capture_output=True, text=True,
                          check=False, timeout=300)
    return proc.returncode, proc.stdout, proc.stderr


_cli_runner: CliRunner = _default_cli_runner


def set_cli_runner(runner: Optional[CliRunner]) -> None:
    """Inject a fake aws CLI for tests (None restores the real one)."""
    global _cli_runner
    _cli_runner = runner or _default_cli_runner


def _aws(region: str, *args: str) -> Any:
    """Run `aws --region <region> <args...> --output json` -> parsed."""
    argv = ['aws', '--region', region, *args, '--output', 'json']
    rc, stdout, stderr = _cli_runner(argv)
    if rc != 0:
        raise exceptions.ProvisionError(
            f'aws {" ".join(args[:2])} failed (rc={rc}): '
            f'{stderr.strip()[:500]}')
    if not stdout.strip():
        return {}
    try:
        return json.loads(stdout)
    except ValueError as e:
        raise exceptions.ProvisionError(
            f'aws returned non-JSON output: {e}') from e


def _describe(region: str, cluster_name: str,
              states: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    filters = [f'Name=tag:{_CLUSTER_TAG},Values={cluster_name}']
    filters.append('Name=instance-state-name,Values=' + ','.join(
        states or ['pending', 'running', 'stopping', 'stopped']))
    out = _aws(region, 'ec2', 'describe-instances',
               '--filters', *filters)
    instances = []
    for reservation in out.get('Reservations', ()):
        instances.extend(reservation.get('Instances', ()))
    return instances


def _tag_value(instance: Dict[str, Any], key: str) -> Optional[str]:
    for tag in instance.get('Tags', ()):
        if tag.get('Key') == key:
            return tag.get('Value')
    return None


_REGION_CACHE: Dict[str, str] = {}


def _remember_region(cluster_name: str, region: str) -> None:
    import os  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    _REGION_CACHE[cluster_name] = region
    path = common_utils.ensure_dir(
        os.path.join(common_utils.skytpu_home(), 'aws_regions'))
    with open(os.path.join(path, cluster_name), 'w',
              encoding='utf-8') as f:
        f.write(region)


def _recall_region(cluster_name: str) -> str:
    import os  # pylint: disable=import-outside-toplevel

    from skypilot_tpu.utils import common_utils  # pylint: disable=import-outside-toplevel
    if cluster_name in _REGION_CACHE:
        return _REGION_CACHE[cluster_name]
    path = os.path.join(common_utils.skytpu_home(), 'aws_regions',
                        cluster_name)
    try:
        with open(path, encoding='utf-8') as f:
            region = f.read().strip()
    except OSError as e:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD) from e
    _REGION_CACHE[cluster_name] = region
    return region


def _resolve_ami(region: str, image_id: Optional[str]) -> str:
    if image_id:
        return image_id
    out = _aws(region, 'ssm', 'get-parameters', '--names', _UBUNTU_SSM)
    params = out.get('Parameters', ())
    if not params:
        raise exceptions.ProvisionError(
            f'Could not resolve the default Ubuntu AMI in {region}.')
    return params[0]['Value']


def _ensure_key_pair(region: str) -> str:
    out = _aws(region, 'ec2', 'describe-key-pairs')
    names = {k.get('KeyName') for k in out.get('KeyPairs', ())}
    if _KEY_NAME in names:
        return _KEY_NAME
    from skypilot_tpu import authentication  # pylint: disable=import-outside-toplevel
    _, public_path = authentication.get_or_generate_keys()
    # fileb:// keeps CLI v2 from base64-decoding the material (raw
    # OpenSSH text would be rejected as invalid base64).
    _aws(region, 'ec2', 'import-key-pair', '--key-name', _KEY_NAME,
         '--public-key-material', f'fileb://{public_path}')
    return _KEY_NAME


def _ensure_security_group(region: str) -> str:
    out = _aws(region, 'ec2', 'describe-security-groups',
               '--filters', f'Name=group-name,Values={_SG_NAME}')
    groups = out.get('SecurityGroups', ())
    if groups:
        return groups[0]['GroupId']
    created = _aws(region, 'ec2', 'create-security-group',
                   '--group-name', _SG_NAME,
                   '--description', 'skypilot_tpu managed')
    group_id = created['GroupId']
    # ssh from anywhere + all traffic within the group (gang comms).
    _aws(region, 'ec2', 'authorize-security-group-ingress',
         '--group-id', group_id, '--protocol', 'tcp', '--port', '22',
         '--cidr', '0.0.0.0/0')
    _aws(region, 'ec2', 'authorize-security-group-ingress',
         '--group-id', group_id, '--protocol', '-1',
         '--source-group', group_id)
    return group_id


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    cluster_name = config.cluster_name
    region = config.region
    deploy_vars = config.deploy_vars
    instance_type = deploy_vars.get('instance_type')
    if not instance_type:
        raise exceptions.ProvisionError(
            'AWS provisioning needs an instance_type (TPUs live on GCP).')
    count = config.count
    _remember_region(cluster_name, region)

    existing = _describe(region, cluster_name)
    created: List[str] = []
    resumed: List[str] = []
    if existing:
        if len(existing) != count:
            raise exceptions.ResourcesMismatchError(
                f'Cluster {cluster_name} exists with {len(existing)} '
                f'nodes; requested {count}.')
        stopping = [i['InstanceId'] for i in existing
                    if i['State']['Name'] == 'stopping']
        if stopping:
            # EC2 rejects start-instances while still 'stopping'.
            _wait_for_state(region, cluster_name, stopping, 'stopped')
        stopped = [i['InstanceId'] for i in existing
                   if i['State']['Name'] in ('stopped', 'stopping')]
        if stopped:
            _aws(region, 'ec2', 'start-instances', '--instance-ids',
                 *stopped)
            resumed = stopped
        _ensure_rank_tags(region, cluster_name)
    else:
        ami = _resolve_ami(region, deploy_vars.get('image_id'))
        key = _ensure_key_pair(region)
        sg = _ensure_security_group(region)
        tag_spec = (
            'ResourceType=instance,Tags=['
            f'{{Key={_CLUSTER_TAG},Value={cluster_name}}}]')
        args = ['ec2', 'run-instances',
                '--image-id', ami,
                '--instance-type', instance_type,
                '--count', str(count),
                '--key-name', key,
                '--security-group-ids', sg,
                '--tag-specifications', tag_spec,
                '--block-device-mappings',
                json.dumps([{
                    'DeviceName': '/dev/sda1',
                    'Ebs': {'VolumeSize':
                            int(deploy_vars.get('disk_size') or 256),
                            'VolumeType': 'gp3'},
                }])]
        if deploy_vars.get('use_spot'):
            args += ['--instance-market-options',
                     json.dumps({'MarketType': 'spot'})]
        if config.zones:
            args += ['--placement',
                     json.dumps({'AvailabilityZone': config.zones[0]})]
        out = _aws(region, *args)
        instances = out.get('Instances', ())
        created = [i['InstanceId'] for i in instances]
        if len(created) != count:
            # All-or-nothing gang, like a TPU slice.
            if created:
                _aws(region, 'ec2', 'terminate-instances',
                     '--instance-ids', *created)
            raise exceptions.ProvisionError(
                f'Requested {count} x {instance_type}, got '
                f'{len(created)}; terminated the partial set.')
        # Stable rank assignment (sorted instance ids).
        for rank, iid in enumerate(sorted(created)):
            _aws(region, 'ec2', 'create-tags', '--resources', iid,
                 '--tags', f'Key={_RANK_TAG},Value={rank}')
    head = sorted([i['InstanceId'] for i in existing] or created)[0]
    return common.ProvisionRecord(
        provider_name='aws',
        cluster_name=cluster_name,
        region=region,
        zone=config.zones[0] if config.zones else '',
        head_instance_id=head,
        created_instance_ids=created,
        resumed_instance_ids=resumed,
    )


def _wait_for_state(region: str, cluster_name: str, ids: List[str],
                    want: str, timeout: float = 300) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        by_id = {i['InstanceId']: i['State']['Name']
                 for i in _describe(region, cluster_name)}
        if all(by_id.get(iid) == want for iid in ids):
            return
        time.sleep(5)
    raise exceptions.ProvisionError(
        f'Instances {ids} did not reach {want!r} within {timeout}s.')


def _ensure_rank_tags(region: str, cluster_name: str) -> None:
    """Assign missing rank tags (sorted instance ids) — a create-tags
    failure mid-provision must not leave a cluster where worker_only
    operations cannot tell the head apart."""
    instances = _describe(region, cluster_name)
    untagged = [i['InstanceId'] for i in instances
                if _tag_value(i, _RANK_TAG) is None]
    if not untagged:
        return
    for rank, iid in enumerate(
            sorted(i['InstanceId'] for i in instances)):
        _aws(region, 'ec2', 'create-tags', '--resources', iid,
             '--tags', f'Key={_RANK_TAG},Value={rank}')


def wait_instances(cluster_name: str, state: Optional[str] = None) -> None:
    region = _recall_region(cluster_name)
    want = state or 'running'
    deadline = time.time() + 600
    while time.time() < deadline:
        instances = _describe(region, cluster_name)
        if instances and all(i['State']['Name'] == want
                             for i in instances):
            return
        time.sleep(5)
    raise exceptions.ProvisionError(
        f'Instances of {cluster_name} did not reach {want!r} in 600s.')


def wait_capacity(cluster_name: str, timeout: float = 0) -> bool:
    del cluster_name, timeout
    return True  # EC2 capacity is synchronous (no queued resources).


def stop_instances(cluster_name: str, worker_only: bool = False) -> None:
    region = _recall_region(cluster_name)
    instances = _describe(region, cluster_name,
                          states=['pending', 'running'])
    ids = [i['InstanceId'] for i in instances
           if not (worker_only and _tag_value(i, _RANK_TAG) == '0')]
    if ids:
        _aws(region, 'ec2', 'stop-instances', '--instance-ids', *ids)


def terminate_instances(cluster_name: str,
                        worker_only: bool = False) -> None:
    region = _recall_region(cluster_name)
    instances = _describe(region, cluster_name)
    ids = [i['InstanceId'] for i in instances
           if not (worker_only and _tag_value(i, _RANK_TAG) == '0')]
    if ids:
        _aws(region, 'ec2', 'terminate-instances', '--instance-ids', *ids)


_STATE_MAP = {
    'pending': ClusterStatus.INIT,
    'running': ClusterStatus.UP,
    'stopping': ClusterStatus.STOPPED,
    'stopped': ClusterStatus.STOPPED,
}


def query_instances(cluster_name: str
                    ) -> Dict[str, Optional[ClusterStatus]]:
    region = _recall_region(cluster_name)
    return {
        i['InstanceId']: _STATE_MAP.get(i['State']['Name'])
        for i in _describe(region, cluster_name)
    }


def get_cluster_info(cluster_name: str,
                     region: Optional[str] = None) -> common.ClusterInfo:
    region = region or _recall_region(cluster_name)
    instances = _describe(region, cluster_name, states=['running'])
    if not instances:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    instances.sort(key=lambda i: int(_tag_value(i, _RANK_TAG) or 0))
    infos = []
    for rank, inst in enumerate(instances):
        infos.append(
            common.InstanceInfo(
                instance_id=inst['InstanceId'],
                internal_ip=inst.get('PrivateIpAddress', ''),
                external_ip=inst.get('PublicIpAddress'),
                ssh_port=22,
                slice_id=0,
                worker_id=rank,
                tags={'rank': str(rank)},
            ))
    from skypilot_tpu import authentication  # pylint: disable=import-outside-toplevel
    private_key, _ = authentication.get_or_generate_keys()
    return common.ClusterInfo(
        provider_name='aws',
        cluster_name=cluster_name,
        region=region,
        zone=instances[0].get('Placement', {}).get('AvailabilityZone'),
        instances=infos,
        head_instance_id=infos[0].instance_id,
        ssh_user=DEFAULT_SSH_USER,
        ssh_private_key=private_key,
    )


def open_ports(cluster_name: str, ports: List[int]) -> None:
    region = _recall_region(cluster_name)
    sg = _ensure_security_group(region)
    for port in ports:
        try:
            _aws(region, 'ec2', 'authorize-security-group-ingress',
                 '--group-id', sg, '--protocol', 'tcp',
                 '--port', str(port), '--cidr', '0.0.0.0/0')
        except exceptions.ProvisionError as e:
            if 'Duplicate' not in str(e):
                raise


def cleanup_ports(cluster_name: str) -> None:
    del cluster_name  # The shared SG persists (reference behavior).


def get_command_runners(cluster_info: common.ClusterInfo,
                        **kwargs: Any) -> List[command_runner.CommandRunner]:
    del kwargs
    runners: List[command_runner.CommandRunner] = []
    for inst in cluster_info.instances:
        ip = inst.external_ip or inst.internal_ip
        runners.append(
            command_runner.SSHCommandRunner(
                node=(ip, inst.ssh_port),
                ssh_user=cluster_info.ssh_user,
                ssh_private_key=cluster_info.ssh_private_key,
                ssh_control_name=cluster_info.cluster_name,
            ))
    return runners
