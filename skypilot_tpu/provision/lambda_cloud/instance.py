"""Lambda Cloud provisioner: REST API with an injectable transport.

Parity: /root/reference/sky/provision/lambda_cloud/instance.py +
lambda_utils.py (~500 LoC of requests calls) — rebuilt on urllib with
`set_api_runner` (the same no-SDK seam as the aws/azure CLI runners),
so the whole lifecycle is unit-testable without credentials or
network.

Lambda's API surface (public v1):
  GET  /instance-types                      offerings + capacity
  GET  /instances                           account's instances
  POST /instance-operations/launch          {region_name,
                                             instance_type_name,
                                             ssh_key_names, quantity,
                                             name} -> instance_ids
  POST /instance-operations/terminate       {instance_ids}
  GET  /ssh-keys  /  POST /ssh-keys         key registry

Cluster identity: every instance is launched with name == the cluster
name; rank = position in the sorted instance-id list (ids are stable
for an instance's lifetime, and Lambda has no stop/resume that could
re-shuffle them).  All-or-nothing gang: a launch shortfall terminates
whatever came up and raises.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.status_lib import ClusterStatus
from skypilot_tpu.utils import command_runner

logger = sky_logging.init_logger(__name__)

_API_BASE = 'https://cloud.lambdalabs.com/api/v1'
DEFAULT_SSH_USER = 'ubuntu'
_KEY_NAME = 'skypilot-tpu'

# Transport seam: runner(method, path, payload|None) -> (status, dict).
ApiRunner = Callable[[str, str, Optional[Dict[str, Any]]],
                     Tuple[int, Dict[str, Any]]]


def _default_api_runner(method: str, path: str,
                        payload: Optional[Dict[str, Any]]
                        ) -> Tuple[int, Dict[str, Any]]:
    from skypilot_tpu.clouds import lambda_cloud  # pylint: disable=import-outside-toplevel
    key = lambda_cloud.read_api_key()
    if not key:
        raise exceptions.ProvisionError(
            'Lambda API key not found (see `sky check`).')
    req = urllib.request.Request(
        _API_BASE + path,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={'Authorization': f'Bearer {key}',
                 'Content-Type': 'application/json'},
        method=method)
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read() or b'{}')
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b'{}')
        except ValueError:
            body = {}
        return e.code, body


_api_runner: ApiRunner = _default_api_runner


def set_api_runner(runner: Optional[ApiRunner]) -> None:
    """Inject a fake Lambda API for tests (None restores the real one)."""
    global _api_runner
    _api_runner = runner or _default_api_runner


def _api(method: str, path: str,
         payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    status, body = _api_runner(method, path, payload)
    if status >= 400:
        err = body.get('error', {})
        raise exceptions.ProvisionError(
            f'Lambda API {method} {path} failed ({status}): '
            f'{err.get("code", "")} {err.get("message", "")}'.strip())
    return body.get('data', body)


def _cluster_instances(cluster_name: str) -> List[Dict[str, Any]]:
    """This cluster's instances, rank-ordered (sorted by id), live
    states only (terminated boxes vanish from /instances anyway)."""
    instances = _api('GET', '/instances')
    mine = [inst for inst in instances
            if inst.get('name') == cluster_name]
    return sorted(mine, key=lambda inst: inst['id'])


def _ensure_ssh_key() -> str:
    from skypilot_tpu import authentication  # pylint: disable=import-outside-toplevel
    _, public_key_path = authentication.get_or_generate_keys()
    with open(public_key_path, encoding='utf-8') as f:
        public_key = f.read().strip()
    for key in _api('GET', '/ssh-keys'):
        if key.get('name') == _KEY_NAME:
            return _KEY_NAME
    _api('POST', '/ssh-keys', {'name': _KEY_NAME,
                               'public_key': public_key})
    return _KEY_NAME


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    cluster_name = config.cluster_name
    region = config.region
    instance_type = config.deploy_vars.get('instance_type')
    if not instance_type:
        raise exceptions.ProvisionError(
            'Lambda provisioning needs an instance_type (TPUs live on '
            'GCP).')
    count = config.count

    existing = _cluster_instances(cluster_name)
    if existing:
        if len(existing) != count:
            raise exceptions.ResourcesMismatchError(
                f'Cluster {cluster_name} exists with {len(existing)} '
                f'nodes; requested {count}.')
        # No stop/resume on Lambda: existing means still running.
        return common.ProvisionRecord(
            provider_name='lambda_cloud', cluster_name=cluster_name,
            region=region, zone=None,
            head_instance_id=existing[0]['id'],
            created_instance_ids=[], resumed_instance_ids=[])

    key_name = _ensure_ssh_key()
    data = _api('POST', '/instance-operations/launch', {
        'region_name': region,
        'instance_type_name': instance_type,
        'ssh_key_names': [key_name],
        'quantity': count,
        'name': cluster_name,
    })
    created = list(data.get('instance_ids', []))
    if len(created) != count:
        # All-or-nothing gang: sweep the partial set and raise.
        if created:
            _api('POST', '/instance-operations/terminate',
                 {'instance_ids': created})
        raise exceptions.ProvisionError(
            f'Requested {count} x {instance_type} in {region}, got '
            f'{len(created)}; terminated the partial set.')
    return common.ProvisionRecord(
        provider_name='lambda_cloud', cluster_name=cluster_name,
        region=region, zone=None,
        head_instance_id=sorted(created)[0],
        created_instance_ids=created, resumed_instance_ids=[])


def wait_instances(cluster_name: str, state: Optional[str] = None) -> None:
    want = state or 'active'
    deadline = time.time() + 900  # bare-metal boots are slow
    while time.time() < deadline:
        instances = _cluster_instances(cluster_name)
        if instances and all(inst.get('status') == want
                             for inst in instances):
            return
        bad = [inst['id'] for inst in instances
               if inst.get('status') in ('unhealthy', 'terminated')]
        if bad:
            raise exceptions.ProvisionError(
                f'Instances {bad} of {cluster_name} became unhealthy '
                'while booting.')
        time.sleep(10)
    raise exceptions.ProvisionError(
        f'Instances of {cluster_name} did not reach {want!r} in 900s.')


def wait_capacity(cluster_name: str, timeout: float = 0) -> bool:
    del cluster_name, timeout
    return True  # launch is synchronous (or fails with no-capacity)


def stop_instances(cluster_name: str, worker_only: bool = False) -> None:
    del cluster_name, worker_only
    raise exceptions.NotSupportedError(
        'Lambda instances cannot be stopped (terminate only).')


def terminate_instances(cluster_name: str,
                        worker_only: bool = False) -> None:
    instances = _cluster_instances(cluster_name)
    if worker_only:
        instances = instances[1:]  # rank 0 is the sorted head
    ids = [inst['id'] for inst in instances]
    if ids:
        _api('POST', '/instance-operations/terminate',
             {'instance_ids': ids})


_STATE_MAP = {
    'active': ClusterStatus.UP,
    'booting': ClusterStatus.INIT,
    'unhealthy': ClusterStatus.INIT,
    'terminating': None,
    'terminated': None,
}


def query_instances(cluster_name: str
                    ) -> Dict[str, Optional[ClusterStatus]]:
    return {
        inst['id']: _STATE_MAP.get(inst.get('status'))
        for inst in _cluster_instances(cluster_name)
    }


def get_cluster_info(cluster_name: str,
                     region: Optional[str] = None) -> common.ClusterInfo:
    instances = [inst for inst in _cluster_instances(cluster_name)
                 if inst.get('status') == 'active']
    if not instances:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    infos = []
    for rank, inst in enumerate(instances):
        infos.append(
            common.InstanceInfo(
                instance_id=inst['id'],
                internal_ip=inst.get('private_ip') or inst['ip'],
                external_ip=inst.get('ip'),
                ssh_port=22,
                slice_id=0,
                worker_id=rank,
                tags={'rank': str(rank)},
            ))
    from skypilot_tpu import authentication  # pylint: disable=import-outside-toplevel
    private_key, _ = authentication.get_or_generate_keys()
    return common.ClusterInfo(
        provider_name='lambda_cloud',
        cluster_name=cluster_name,
        region=region or (instances[0].get('region') or {}).get('name', ''),
        zone=None,
        instances=infos,
        head_instance_id=infos[0].instance_id,
        ssh_user=DEFAULT_SSH_USER,
        ssh_private_key=private_key,
    )


def open_ports(cluster_name: str, ports: List[int]) -> None:
    del cluster_name
    # Account-level firewall only; the cloud class declares OPEN_PORTS
    # unsupported so the optimizer never routes port-requiring tasks
    # here — reaching this is a bug, not a no-op.
    raise exceptions.NotSupportedError(
        f'Lambda has no per-instance port API (requested {ports}).')


def cleanup_ports(cluster_name: str) -> None:
    del cluster_name  # nothing was opened


def get_command_runners(cluster_info: common.ClusterInfo,
                        **kwargs: Any) -> List[command_runner.CommandRunner]:
    del kwargs
    runners: List[command_runner.CommandRunner] = []
    for inst in cluster_info.instances:
        ip = inst.external_ip or inst.internal_ip
        runners.append(
            command_runner.SSHCommandRunner(
                node=(ip, inst.ssh_port),
                ssh_user=cluster_info.ssh_user,
                ssh_private_key=cluster_info.ssh_private_key,
                ssh_control_name=cluster_info.cluster_name,
            ))
    return runners
