"""Lambda Cloud provisioner package."""
