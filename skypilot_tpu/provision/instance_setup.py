"""Per-host runtime bootstrap, fanned out in parallel.

Parity: /root/reference/sky/provision/instance_setup.py:70-510
(`_auto_retry`, internal file mounts, runtime setup, skylet start) — minus
Ray: there is no `start_ray_on_head/workers`; the remote runtime is just the
app package + the skylet daemon on the head host, and gang execution happens
over the command runners directly.
"""
from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Dict, List, Optional

import skypilot_tpu
from skypilot_tpu import sky_logging
from skypilot_tpu.skylet import constants
from skypilot_tpu.utils import command_runner as command_runner_lib
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

_MAX_RETRY = 3


def _auto_retry(func: Callable) -> Callable:
    """Retry transient host failures (parity instance_setup.py:70)."""

    @functools.wraps(func)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        backoff = 1.0
        for attempt in range(_MAX_RETRY):
            try:
                return func(*args, **kwargs)
            except Exception as e:  # pylint: disable=broad-except
                if attempt == _MAX_RETRY - 1:
                    raise
                logger.warning(f'{func.__name__} failed '
                               f'(attempt {attempt + 1}/{_MAX_RETRY}): {e}')
                time.sleep(backoff)
                backoff *= 2

    return wrapper


def _app_package_source() -> str:
    """The installed skypilot_tpu package tree (shipped to every host).

    Replaces the reference's wheel build+install
    (backends/wheel_utils.py:1-60): a direct package-tree sync has the same
    idempotency with none of the ~2s wheel-build latency
    (reference cloud_vm_ray_backend.py:2747).
    """
    return os.path.dirname(os.path.abspath(skypilot_tpu.__file__))


@_auto_retry
def _mount_app_on_host(runner: command_runner_lib.CommandRunner) -> None:
    app_dir = constants.SKY_REMOTE_APP_DIR
    runner.run(f'mkdir -p {app_dir}', stream_logs=False)
    runner.rsync(_app_package_source(), f'{app_dir}/skypilot_tpu', up=True,
                 stream_logs=False)


def internal_file_mounts(
        runners: List[command_runner_lib.CommandRunner],
        credential_files: Optional[Dict[str, str]] = None) -> None:
    """Ship the app package (+ cloud credentials) to every host in parallel.

    Parity: reference instance_setup.py:490 internal_file_mounts (wheel,
    credentials, catalogs).
    """

    def _one(runner: command_runner_lib.CommandRunner) -> None:
        _mount_app_on_host(runner)
        for dst, src in (credential_files or {}).items():
            expanded = os.path.expanduser(src)
            if os.path.exists(expanded):
                parent = os.path.dirname(dst.rstrip('/'))
                if parent and parent not in ('~', '/'):
                    runner.run(f'mkdir -p {parent}', stream_logs=False)
                runner.rsync(expanded, dst, up=True, stream_logs=False)

    subprocess_utils.run_in_parallel(_one, runners)


def setup_runtime_on_cluster(
        runners: List[command_runner_lib.CommandRunner]) -> None:
    """Create the standard directory layout on every host."""

    @_auto_retry
    def _one(runner: command_runner_lib.CommandRunner) -> None:
        returncode = runner.run(constants.RUNTIME_SETUP_COMMANDS,
                                stream_logs=False)
        if returncode != 0:
            raise RuntimeError(
                f'Runtime setup failed on {runner.node_id} '
                f'(rc={returncode}).')

    subprocess_utils.run_in_parallel(_one, runners)


@_auto_retry
def start_skylet_on_head_node(
        head_runner: command_runner_lib.CommandRunner) -> None:
    """(Re)start the skylet daemon on the head host; idempotent."""
    returncode = head_runner.run(constants.SKYLET_START_COMMAND,
                                 stream_logs=False)
    if returncode != 0:
        raise RuntimeError(
            f'Failed to start skylet on {head_runner.node_id} '
            f'(rc={returncode}).')
